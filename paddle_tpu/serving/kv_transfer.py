"""KV-page wire transfer for disaggregated prefill/decode serving.

The disaggregation split (docs/serving.md "Disaggregated serving"):
prefill replicas run big-bucket prefill only and hand the finished KV
pages to a decode replica, which installs them into its own pool and
enters the normal harvest pipeline. This module is the WIRE between
them: a host-side block-scaled codec for the page payload (riding the
same block math as ``distributed/compression.quantize_blocks``), the
chunked TCPStore publish/fetch protocol (the store's ``get`` caps one
value at 1MB), and the handoff metadata that lets the decode replica
reconstruct the request's exact device state (lengths / last token /
budget / eos) so decode continues bit-for-bit where prefill stopped.

Wire formats (``PT_KV_WIRE``, default ``int8``):

- ``fp32``: the pool bytes verbatim — the bit-identity reference: a
  request served prefill→transfer→decode produces the exact token
  stream of same-replica serving (asserted by tests/test_serve_disagg
  and the ``tools/ci.sh disagg`` smoke).
- ``int8`` / ``fp8``: block-scaled (one fp32 scale per ``PT_COMM_BLOCK``
  values, int8 ±127 / e4m3 ±448) — ~3.94x fewer wire bytes at the
  default block, metered by ``serve/kv_transfer_bytes_wire`` vs
  ``serve/kv_transfer_bytes_logical``. Per-element error is bounded by
  the block's own half step (``amax_block / (2*qmax)``), the bound the
  divergence test pins.

**Fail-loud scale-integrity guard** (same contract as
``collective.quant_payload``, PR 7): the header carries the
pre-quantization global ``amax``; the decoder validates every block
scale (finite, inside the amax envelope) and every dequantized value
(finite, bounded) and RAISES on violation — a corrupted scale must
never install plausible-looking KV. The fault site
``kv_transfer.payload`` bitflips a scale (default) or payload byte
between encode and publish; a flipped PAYLOAD byte remains a valid
in-envelope code whose damage is bounded by its block scale — the
guard's guarantee is scale integrity, not payload integrity.

Everything here is host-side numpy — nothing traced, importable by the
router process without touching a device.
"""

import collections
import hashlib
import io
import json
import os
import struct
import threading
import time
from typing import Optional, Tuple

import numpy as np

__all__ = ["wire_format", "encode_kv_pages", "decode_kv_pages",
           "publish_blob", "fetch_blob", "delete_blob", "WIRE_FORMATS",
           "kv_transport", "maybe_transport", "KVTransport",
           "send_handoff", "fetch_handoff", "delete_handoff"]

WIRE_FORMATS = ("fp32", "int8", "fp8")
_FAULT_SITE = "kv_transfer.payload"
# one store value must stay under native.TCPStore.get's 1MB buffer
_CHUNK = 768 * 1024


def wire_format(wire: Optional[str] = None) -> str:
    """Resolve the KV wire format: explicit arg beats ``PT_KV_WIRE``
    beats the int8 default. ``fp32`` is the bit-identity opt-out."""
    w = wire or os.environ.get("PT_KV_WIRE", "int8").strip().lower()
    if w in ("fp32", "none", "off", "raw"):
        return "fp32"
    if w not in WIRE_FORMATS:
        raise ValueError(
            f"PT_KV_WIRE must be one of {WIRE_FORMATS}, got {w!r}")
    return w


def _block() -> int:
    return int(os.environ.get("PT_COMM_BLOCK", "256"))


def _np_wire_dtype(wire: str):
    if wire == "int8":
        return np.dtype(np.int8), 127.0
    from paddle_tpu import dtypes
    return np.dtype(dtypes.float8_e4m3), 448.0


def _quantize_np(flat: np.ndarray, wire: str, block: int):
    """Host-side mirror of ``compression.quantize_blocks`` (same block
    clamp for tiny tensors, same scale floor): fp32 1-D in, returns
    (payload, scales (nb,1) fp32, n)."""
    dt, qmax = _np_wire_dtype(wire)
    n = flat.size
    block = max(1, min(block, n))
    nb = -(-n // block)
    padded = np.zeros((nb * block,), np.float32)
    padded[:n] = flat
    blocks = padded.reshape(nb, block)
    amax = np.max(np.abs(blocks), axis=1, keepdims=True)
    scales = (amax / qmax + 1e-30).astype(np.float32)
    if wire == "int8":
        payload = np.clip(np.round(blocks / scales), -qmax,
                          qmax).astype(dt)
    else:
        payload = (blocks / scales).astype(dt)
    return payload, scales, n


def _inject_fault(scales_bytes: bytes, payload_bytes: bytes):
    """Fault site ``kv_transfer.payload``: a matching bitflip/truncate
    rule corrupts the scale bytes (default target) or the payload bytes
    between encode and the wire. Inert without a fault plan."""
    from paddle_tpu.testing import faults
    if not faults.enabled():
        return scales_bytes, payload_bytes
    for kw in faults.spec(_FAULT_SITE, actions=("bitflip",)):
        off = int(kw.get("offset", 0))
        bit = int(kw.get("bit", 30))
        if str(kw.get("target", "scale")) == "payload":
            b = bytearray(payload_bytes)
            if b:
                b[off % len(b)] ^= 1 << (bit % 8)
            payload_bytes = bytes(b)
        else:
            b = bytearray(scales_bytes)
            if b:
                b[off % len(b)] ^= 1 << (bit % 8)
            scales_bytes = bytes(b)
    return scales_bytes, payload_bytes


def encode_kv_pages(k: np.ndarray, v: np.ndarray, n_tokens: int,
                    wire: Optional[str] = None,
                    block: Optional[int] = None,
                    rid: Optional[str] = None
                    ) -> Tuple[dict, bytes]:
    """Serialize one request's KV pages for the wire.

    ``k``/``v``: (L, npages, Hkv, page, D) host arrays in the pool
    dtype. Rows at positions >= ``n_tokens`` of the last page are
    ZEROED first — they hold recycled-pool garbage the decode side must
    not inherit (decode overwrites them before ever reading, so this
    cannot change outputs; it keeps the wire deterministic and the
    compression honest). Returns ``(header, blob)``; the header is
    JSON-serializable and carries the scale-integrity envelope, plus
    the request's trace context (``rid``) when given — the receiving
    replica's spans for these pages stitch onto the same fleet-wide
    request timeline.
    """
    wire = wire_format(wire)
    block = block if block is not None else _block()
    L, npg, hkv, page, d = k.shape
    # ALWAYS copy: the tail zeroing below is wire-local and must never
    # mutate the caller's buffers (device views arrive read-only
    # anyway; a writable caller array re-used after encode would
    # otherwise lose its tail rows silently)
    k = np.array(k, dtype=k.dtype, copy=True, order="C")
    v = np.array(v, dtype=v.dtype, copy=True, order="C")
    tail = int(n_tokens) % page
    if npg and tail:
        k[:, npg - 1, :, tail:, :] = 0
        v[:, npg - 1, :, tail:, :] = 0
    logical = k.nbytes + v.nbytes
    header = {
        "wire": wire, "block": int(block),
        "pool_dtype": k.dtype.name, "shape": [L, npg, hkv, page, d],
        "n_tokens": int(n_tokens), "bytes_logical": int(logical),
    }
    if rid is not None:
        header["rid"] = str(rid)
    buf = io.BytesIO()
    if wire == "fp32":
        buf.write(k.tobytes())
        buf.write(v.tobytes())
        header["sections"] = [["k", k.nbytes], ["v", v.nbytes]]
    else:
        _, qmax = _np_wire_dtype(wire)
        sections = []
        amaxes = {}
        for name, arr in (("k", k), ("v", v)):
            flat = np.asarray(arr, np.float32).reshape(-1)
            amaxes[name] = float(np.max(np.abs(flat))) if flat.size \
                else 0.0
            payload, scales, _ = _quantize_np(flat, wire, block)
            sb, pb = _inject_fault(scales.tobytes(), payload.tobytes())
            buf.write(pb)
            buf.write(sb)
            sections.append([name, len(pb), len(sb),
                             int(payload.shape[1])])
        header["sections"] = sections
        header["amax"] = amaxes          # the guard envelope
        header["qmax"] = qmax
    blob = buf.getvalue()
    header["bytes_wire"] = len(blob)
    # whole-blob content digest: the fp32 wire has no quantization
    # envelope to catch in-transit corruption (the quantized guard
    # below is scale integrity, not payload integrity) — a migrated
    # mid-decode handoff bitflipped on the wire must fail the fetch
    # loudly, never install and silently fork the stream
    header["sha256"] = hashlib.sha256(blob).hexdigest()
    from paddle_tpu import stats
    stats.add("serve/kv_transfer_bytes_logical", logical)
    stats.add("serve/kv_transfer_bytes_wire", len(blob))
    if len(blob):
        stats.set_value("serve/kv_transfer_ratio", logical / len(blob))
    return header, blob


def decode_kv_pages(header: dict, blob: bytes,
                    strict: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`encode_kv_pages` — returns (k, v) in the pool
    dtype. On the quantized wire every block scale and every
    dequantized value is validated against the header's amax envelope;
    a violation raises RuntimeError (fail-loud: corrupted KV must never
    install silently). ``strict=False`` returns NaN-poisoned pages
    instead of raising (callers that prefer the engine's own
    non-finite eviction to surface the failure)."""
    wire = header["wire"]
    L, npg, hkv, page, d = header["shape"]
    shape = (L, npg, hkv, page, d)
    dt = np.dtype(header["pool_dtype"])
    n = int(np.prod(shape))
    want = header.get("sha256")
    if want is not None and hashlib.sha256(blob).hexdigest() != want:
        # in-transit corruption (bitflip/truncate on ANY wire): the
        # blob no longer matches what the sender encoded
        if strict:
            raise RuntimeError(
                "KV blob failed content-digest validation — in-transit "
                "corruption; refusing to install corrupted pages")
        k = np.full(shape, np.nan, dt)
        return k, k.copy()
    if wire == "fp32":
        (kn, kb), (vn, vb) = header["sections"]
        k = np.frombuffer(blob[:kb], dt).reshape(shape)
        v = np.frombuffer(blob[kb:kb + vb], dt).reshape(shape)
        return k.copy(), v.copy()
    wdt, qmax = _np_wire_dtype(wire)
    out = {}
    off = 0
    bad = None
    for name, pb, sb, blk in header["sections"]:
        payload = np.frombuffer(blob[off:off + pb], wdt)
        off += pb
        scales = np.frombuffer(blob[off:off + sb], np.float32)
        off += sb
        amax = float(header["amax"][name])
        # scale integrity: finite, non-negative, inside the envelope
        # the pre-quantization maxima allow (4x slack mirrors
        # compression._wire_ok); a flipped high bit lands far outside
        smax = amax / float(header["qmax"]) + 1e-6
        if (not np.all(np.isfinite(scales)) or np.any(scales < 0)
                or np.any(scales > 4.0 * smax + 1e-30)):
            bad = f"corrupted block scale in {name!r} section"
        with np.errstate(over="ignore"):
            # a corrupted scale can overflow fp32 here — that is
            # exactly what the envelope check below catches
            deq = (payload.astype(np.float32).reshape(-1, blk)
                   * scales.reshape(-1, 1)).reshape(-1)[:n]
        if not np.all(np.isfinite(deq)) or (
                deq.size and np.max(np.abs(deq)) > 4.0 * amax + 1e-6):
            bad = bad or f"dequantized {name!r} outside amax envelope"
        out[name] = deq.reshape(shape).astype(dt)
    if bad is not None:
        if strict:
            raise RuntimeError(
                f"KV wire failed scale-integrity validation ({bad}); "
                f"fault site {_FAULT_SITE!r} — refusing to install "
                "corrupted pages")
        for name in out:
            out[name] = np.full(shape, np.nan, dt)
    return out["k"], out["v"]


# ---------------------------------------------------------------------------
# Chunked store transport (native TCPStore values cap at 1MB per get)
# ---------------------------------------------------------------------------

def publish_blob(store, key: str, header: dict, blob: bytes):
    """Write ``header`` + ``blob`` under ``key`` on the store, blob
    split into <1MB chunks. The meta key is written LAST so a reader
    that sees it can fetch every chunk — a writer killed mid-transfer
    leaves no meta key and therefore no torn read."""
    nchunks = -(-len(blob) // _CHUNK) if blob else 0
    for i in range(nchunks):
        store.set(f"{key}/c{i}", blob[i * _CHUNK:(i + 1) * _CHUNK])
    meta = dict(header, nchunks=nchunks)
    store.set(f"{key}/meta", json.dumps(meta))


def fetch_blob(store, key: str, timeout: float = 5.0
               ) -> Tuple[dict, bytes]:
    """Read back one published blob (meta + chunks). Raises
    TimeoutError when the meta key is absent (transfer incomplete or
    withdrawn)."""
    meta = json.loads(store.get(f"{key}/meta", timeout=timeout))
    parts = [store.get(f"{key}/c{i}", timeout=timeout)
             for i in range(int(meta["nchunks"]))]
    return meta, b"".join(parts)


def delete_blob(store, key: str, nchunks: Optional[int] = None):
    """Withdraw a published blob: the meta key FIRST (no new readers),
    then the chunks."""
    if nchunks is None:
        try:
            nchunks = int(json.loads(
                store.get(f"{key}/meta", timeout=0.05))["nchunks"])
        except Exception:
            nchunks = 0
    try:
        store.delete_key(f"{key}/meta")
        for i in range(int(nchunks)):
            store.delete_key(f"{key}/c{i}")
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Store-bypassing socket transport (ISSUE 17 tentpole 3)
# ---------------------------------------------------------------------------
#
# On the default ``PT_KV_TRANSPORT=socket`` plane, handoff / migration
# blobs move over direct replica-to-replica ``native.P2PEndpoint``
# sockets instead of 1MB store chunks: the sender keeps each encoded
# blob in a bounded outbox and answers tag-addressed FETCH requests;
# the router forwards only the sender's ``[host, port]`` locator in the
# handoff message. The store keeps membership + directory + small
# results only, so a router failover never re-hosts KV bytes and the
# single-store byte ceiling is gone (``serve/kv_transport_bytes_store``
# stays ~flat while ``_socket`` grows). The codec — and with it the
# sha256 digest + scale-integrity guard — is exactly the store path's:
# only the carrier changes.
#
# Wire format, one framed P2P message per direction (docs/fleet-ha.md):
#
#   control (tag 0, JSON):  {"op": "fetch", "key", "host", "port", "tag"}
#                           {"op": "del",   "key"}
#   reply  (requester tag): u64 header_len (big-endian) + header JSON +
#                           blob; header_len == 0 encodes a MISS (the
#                           requester raises TimeoutError — the same
#                           retryable signal as an absent store meta
#                           key, so the router's handoff-failed
#                           re-place path applies unchanged).

_CTRL_TAG = 0


def kv_transport(mode: Optional[str] = None) -> str:
    """Resolve the KV data plane: ``socket`` (default — direct
    replica-to-replica P2P) or ``store`` (the PR 13 chunked TCPStore
    path, also the automatic fallback when the native lib is absent).
    Must agree fleet-wide: a store-mode receiver cannot fetch a
    socket-mode sender's blob (it degrades to handoff-failed
    re-placement, not corruption)."""
    m = (mode or os.environ.get("PT_KV_TRANSPORT", "socket")) \
        .strip().lower()
    if m not in ("socket", "store"):
        raise ValueError(
            f"PT_KV_TRANSPORT must be socket|store, got {m!r}")
    return m


def serve_host() -> str:
    """The host peers dial this replica's KV endpoint on
    (``PT_SERVE_HOST``, default loopback — single-host fleets)."""
    return os.environ.get("PT_SERVE_HOST", "127.0.0.1")


def maybe_transport(mode: Optional[str] = None) -> Optional["KVTransport"]:
    """A `KVTransport` when the socket plane is selected and the native
    lib is present; None otherwise (callers then use the store path)."""
    from paddle_tpu import native
    if kv_transport(mode) != "socket" or not native.is_available():
        return None
    try:
        return KVTransport()
    except Exception:
        return None             # no listen socket → degrade to store


class KVTransport:
    """One replica's end of the socket KV data plane: a
    ``native.P2PEndpoint`` (ephemeral port), a bounded blob outbox, and
    the fetch/del control protocol above.

    A daemon pump thread answers peers' control messages so fetches
    are served even while the owning serve loop is deep inside a long
    ``engine.step()`` (a jax bucket compile can park the loop for
    seconds — a peer's 2s fetch must not starve meanwhile). Every
    endpoint/outbox touch is serialized by one lock; the serve loop's
    :meth:`pump` call is kept as a no-cost assist, and :meth:`fetch`
    still pumps while it waits so two replicas fetching from each
    other (migration storms) cannot deadlock even without the
    thread."""

    MAX_OUTBOX = 32             # evicted blobs degrade to handoff-failed

    def __init__(self, port: int = 0):
        from paddle_tpu import native
        self.ep = native.P2PEndpoint(port)
        self.host = serve_host()
        self.port = self.ep.port
        self.outbox = collections.OrderedDict()   # key -> (header, blob)
        self._tag = 1 << 32     # reply tags; 0 is the control tag
        self._lock = threading.RLock()
        self._closed = False
        self._thread = threading.Thread(
            target=self._pump_loop, name=f"kv-transport-{self.port}",
            daemon=True)
        self._thread.start()

    def locator(self) -> Tuple[str, int]:
        return (self.host, self.port)

    # -- sender side ----------------------------------------------------
    def offer(self, key: str, header: dict, blob: bytes):
        from paddle_tpu import stats
        with self._lock:
            self.outbox[key] = (dict(header, nchunks=0), bytes(blob))
            self.outbox.move_to_end(key)
            while len(self.outbox) > self.MAX_OUTBOX:
                self.outbox.popitem(last=False)
                stats.add("serve/kv_transport_evicted")
        stats.add("serve/kv_transport_offers")

    def withdraw(self, key: str):
        with self._lock:
            self.outbox.pop(key, None)

    def _pump_loop(self):
        while not self._closed:
            try:
                n = self.pump()
            except Exception:
                n = 0           # a poisoned ctrl frame never kills it
            time.sleep(0.002 if n else 0.02)

    def pump(self, budget: int = 8) -> int:
        """Answer up to ``budget`` queued control messages (non-
        blocking); returns how many were handled. A reply the requester
        can no longer receive is dropped — it times out and the router
        re-places."""
        from paddle_tpu import stats
        handled = 0
        for _ in range(budget):
            with self._lock:
                if self._closed:
                    return handled
                try:
                    raw = self.ep.recv(_CTRL_TAG, timeout=0.0)
                except TimeoutError:
                    return handled
                except RuntimeError:
                    continue
                handled += 1
                try:
                    msg = json.loads(raw)
                except ValueError:
                    continue
                if msg.get("op") == "del":
                    self.outbox.pop(msg.get("key"), None)
                    continue
                if msg.get("op") != "fetch":
                    continue
                ent = self.outbox.get(msg.get("key"))
                if ent is None:
                    payload = struct.pack(">Q", 0)
                    stats.add("serve/kv_transport_misses")
                else:
                    header, blob = ent
                    hj = json.dumps(header).encode()
                    payload = struct.pack(">Q", len(hj)) + hj + blob
                    stats.add("serve/kv_transport_bytes_socket",
                              len(blob))
                try:
                    self.ep.send(msg["host"], int(msg["port"]),
                                 int(msg["tag"]), payload)
                except (ConnectionError, BrokenPipeError, KeyError,
                        ValueError, TypeError):
                    pass
        return handled

    # -- receiver side --------------------------------------------------
    def fetch(self, host: str, port: int, key: str,
              timeout: float = 5.0) -> Tuple[dict, bytes]:
        """Fetch ``key`` from the owner at ``host:port``. Raises
        TimeoutError on an unreachable/evicted/absent blob — the same
        retryable contract as :func:`fetch_blob`."""
        from paddle_tpu import stats
        with self._lock:
            self._tag += 1
            tag = self._tag
        ctrl = json.dumps({"op": "fetch", "key": key, "host": self.host,
                           "port": self.port, "tag": tag}).encode()
        try:
            with self._lock:
                self.ep.send(host, int(port), _CTRL_TAG, ctrl)
        except (ConnectionError, BrokenPipeError) as e:
            raise TimeoutError(
                f"kv socket fetch({key}): owner {host}:{port} "
                f"unreachable: {e}") from e
        deadline = time.monotonic() + timeout
        while True:
            self.pump()         # keep answering peers while we wait
            try:
                with self._lock:
                    reply = self.ep.recv(tag, timeout=0.05)
                break
            except TimeoutError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"kv socket fetch({key}) from {host}:{port} "
                        f"timed out after {timeout}s")
        hlen = struct.unpack(">Q", reply[:8])[0]
        if hlen == 0:
            raise TimeoutError(
                f"kv socket fetch({key}): blob absent at owner "
                f"{host}:{port} (withdrawn or evicted)")
        header = json.loads(reply[8:8 + hlen])
        blob = reply[8 + hlen:]
        stats.add("serve/kv_transport_bytes_socket", len(blob))
        return header, blob

    def delete(self, host: str, port: int, key: str):
        """Best-effort del notice to the owner (fire-and-forget)."""
        try:
            with self._lock:
                self.ep.send(host, int(port), _CTRL_TAG,
                             json.dumps({"op": "del",
                                         "key": key}).encode())
        except (ConnectionError, BrokenPipeError):
            pass

    def close(self):
        self._closed = True
        with self._lock:
            self.outbox.clear()
            try:
                self.ep.close()
            except Exception:
                pass


# -- transport-forking handoff helpers (the serve loops' one entry) ---------

def send_handoff(store, transport: Optional[KVTransport], key: str,
                 header: dict, blob: bytes):
    """Publish a handoff/migration blob on the configured data plane.
    Returns the locator the router forwards to the receiving replica:
    ``[host, port]`` = fetch over the socket plane from the owner,
    ``None`` = the chunked store path."""
    from paddle_tpu import stats
    if transport is not None:
        transport.offer(key, header, blob)
        return list(transport.locator())
    stats.add("serve/kv_transport_bytes_store", len(blob))
    publish_blob(store, key, header, blob)
    return None


def fetch_handoff(store, transport: Optional[KVTransport], key: str,
                  kv_ep=None, timeout: float = 5.0) -> Tuple[dict, bytes]:
    """Fetch a handoff blob from wherever ``kv_ep`` says it lives.
    Raises TimeoutError (retryable — router re-places) when absent on
    either plane, including the mixed-config case of a socket locator
    with no local transport."""
    from paddle_tpu import stats
    if kv_ep:
        if transport is None:
            raise TimeoutError(
                f"handoff {key} lives on the socket plane at "
                f"{kv_ep[0]}:{kv_ep[1]} but this replica has no "
                f"transport (PT_KV_TRANSPORT mismatch)")
        return transport.fetch(kv_ep[0], int(kv_ep[1]), key,
                               timeout=timeout)
    header, blob = fetch_blob(store, key, timeout=timeout)
    stats.add("serve/kv_transport_bytes_store", len(blob))
    return header, blob


def delete_handoff(store, transport: Optional[KVTransport], key: str,
                   kv_ep=None, nchunks: Optional[int] = None):
    """Withdraw an installed handoff blob from its plane."""
    if kv_ep:
        if transport is not None:
            transport.delete(kv_ep[0], int(kv_ep[1]), key)
        return
    delete_blob(store, key, nchunks=nchunks)
