"""Deterministic load generation for the serving front-end.

Two sources, one shape: a list of :class:`Arrival` records (arrival
offset in seconds, prompt, decode budget, optional deadline/priority).

- :func:`poisson_trace` — synthetic open-loop Poisson traffic at an
  offered QPS: exponential inter-arrivals, uniform prompt/decode
  lengths, all drawn from ONE seeded ``RandomState`` so a (seed, qps,
  n) triple is bit-reproducible across processes and rounds — the SLO
  bench's ladder rows and the CI smoke replay the identical workload.
- :func:`from_trace` — trace-driven replay of recorded traffic
  (dicts with ``t``/``prompt``/``max_new_tokens``...), for feeding
  production request logs through the scheduler.

:func:`replay` paces the arrivals against the wall clock in open-loop
style (a late server does NOT slow the generator down — that would
hide queueing collapse, the thing an SLO bench exists to show) and
keeps the front-end pumping while it waits.
"""

import os
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["Arrival", "poisson_trace", "from_trace", "replay",
           "replay_ticks", "default_seed"]


def default_seed() -> int:
    """The load-generator seed (``PT_SERVE_LOADGEN_SEED``): one knob so
    bench rows and CI smokes pin the exact same workload."""
    return int(os.environ.get("PT_SERVE_LOADGEN_SEED", "0"))


@dataclass
class Arrival:
    t: float                    # seconds after replay start
    prompt: List[int]
    max_new_tokens: int
    deadline_s: Optional[float] = None
    priority: int = 0


def poisson_trace(n: int, qps: float, seed: Optional[int] = None,
                  vocab: int = 96, prompt_len=(4, 48),
                  new_tokens=(4, 24),
                  deadline_s: Optional[float] = None) -> List[Arrival]:
    """``n`` arrivals at offered rate ``qps`` (exponential
    inter-arrival gaps), prompts/budgets uniform over the given
    ``[lo, hi]`` ranges. Deterministic in (seed, n, qps, ranges)."""
    if n < 1 or qps <= 0:
        raise ValueError(f"need n >= 1 arrivals at qps > 0, "
                         f"got n={n} qps={qps}")
    rs = np.random.RandomState(default_seed() if seed is None else seed)
    gaps = rs.exponential(1.0 / qps, size=n)
    gaps[0] = 0.0               # first request lands at t=0
    times = np.cumsum(gaps)
    out = []
    for i in range(n):
        plen = int(rs.randint(prompt_len[0], prompt_len[1] + 1))
        out.append(Arrival(
            t=float(times[i]),
            prompt=[int(x) for x in rs.randint(0, vocab, size=plen)],
            max_new_tokens=int(rs.randint(new_tokens[0],
                                          new_tokens[1] + 1)),
            deadline_s=deadline_s))
    return out


def from_trace(rows: Sequence[dict]) -> List[Arrival]:
    """Trace-driven arrivals from recorded rows (``t`` seconds,
    ``prompt``, ``max_new_tokens``, optional ``deadline_s`` /
    ``priority``), sorted by arrival time."""
    out = [Arrival(t=float(r["t"]), prompt=list(r["prompt"]),
                   max_new_tokens=int(r["max_new_tokens"]),
                   deadline_s=r.get("deadline_s"),
                   priority=int(r.get("priority", 0)))
           for r in rows]
    return sorted(out, key=lambda a: a.t)


def replay(arrivals: Sequence[Arrival], submit: Callable,
           pump: Optional[Callable] = None, speed: float = 1.0) -> list:
    """Open-loop replay: submit each arrival at its wall-clock offset
    (scaled by ``speed``; 2.0 = twice as fast), calling ``pump()``
    (typically ``frontend.step``) while waiting so the server keeps
    serving between arrivals. Returns the ``submit`` results in arrival
    order. Draining after the last arrival is the caller's job."""
    handles = []
    t0 = time.perf_counter()
    for a in arrivals:
        due = a.t / speed
        while time.perf_counter() - t0 < due:
            if pump is not None:
                pump()
            else:
                time.sleep(0.001)
        handles.append(submit(a))
    return handles


def replay_ticks(arrivals: Sequence[Arrival], submit: Callable,
                 pump: Callable, tick_s: float = 1.0) -> list:
    """Deterministic closed-clock replay (the CI-smoke de-flake
    idiom): the clock advances a fixed ``tick_s`` virtual seconds per
    ``pump()`` call instead of reading the wall clock, so a loaded
    host (a concurrent test suite stealing the CPU between pumps)
    can neither bunch the arrivals together nor starve the server of
    pump calls between them — the interleaving of arrivals and serve
    steps is a pure function of the trace. Offered load is therefore
    expressed in pumps, not seconds: a trace generated at ``qps=q``
    replayed at ``tick_s=1.0`` delivers ``q`` arrivals per pump call.
    Wall-clock :func:`replay` stays the real SLO-bench pacing — this
    one is for assertions that must hold under any machine load."""
    handles = []
    vt = 0.0
    for a in arrivals:
        while vt < a.t:
            pump()
            vt += tick_s
        handles.append(submit(a))
    return handles
