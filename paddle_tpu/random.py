"""Explicit-key RNG with named streams.

Reference analogs:
- phi::Generator per-device engines + paddle.seed
  (paddle/phi/core/generator.h:23);
- model-parallel determinism via ``RNGStatesTracker``
  (python/paddle/distributed/fleet/layers/mpu/random.py:32) — named seed
  streams so tensor-parallel dropout draws per-rank-distinct or replicated
  noise by choice.

JAX already gives deterministic splittable keys; this module layers on top:
a global seed, a monotone draw counter per *named stream*, and a context
manager to switch streams (TP layers use stream "model_parallel").
"""

import contextlib
import threading
from typing import Dict, Optional

import jax

_state = threading.local()


class _Stream:
    __slots__ = ("seed", "counter")

    def __init__(self, seed: int):
        self.seed = seed
        self.counter = 0


class RNGStatesTracker:
    """Named RNG streams (ref: mpu/random.py:32 RNGStatesTracker)."""

    def __init__(self):
        self._streams: Dict[str, _Stream] = {}
        self._current = "global"
        self._streams["global"] = _Stream(0)

    def add(self, name: str, seed: int) -> None:
        if name in self._streams and self._streams[name].seed != seed:
            raise ValueError(f"stream {name!r} already added with a different seed")
        self._streams.setdefault(name, _Stream(seed))

    @contextlib.contextmanager
    def rng_state(self, name: str = "model_parallel"):
        if name not in self._streams:
            raise KeyError(f"unknown rng stream {name!r}; call add() first")
        prev, self._current = self._current, name
        try:
            yield
        finally:
            self._current = prev

    def next_key(self, stream: Optional[str] = None) -> jax.Array:
        s = self._streams[stream or self._current]
        s.counter += 1
        return jax.random.fold_in(jax.random.key(s.seed), s.counter)

    def state_dict(self):
        return {k: (v.seed, v.counter) for k, v in self._streams.items()}

    def load_state_dict(self, state):
        for k, (seed, counter) in state.items():
            st = self._streams.setdefault(k, _Stream(seed))
            st.seed, st.counter = seed, counter


_tracker = RNGStatesTracker()


def default_tracker() -> RNGStatesTracker:
    return _tracker


def seed(s: int) -> None:
    """Set the global seed (ref: paddle.seed). Resets all stream counters."""
    _tracker._streams["global"] = _Stream(int(s))
    for name, st in _tracker._streams.items():
        if name != "global":
            st.counter = 0


def next_key(stream: Optional[str] = None) -> jax.Array:
    """Draw the next PRNG key from a named stream (default: current)."""
    return _tracker.next_key(stream)


def split_key(key: Optional[jax.Array] = None, num: int = 2):
    key = key if key is not None else next_key()
    return jax.random.split(key, num)


def get_rng_state():
    return _tracker.state_dict()


def set_rng_state(state):
    _tracker.load_state_dict(state)


@contextlib.contextmanager
def rng_state(name: str):
    """Switch the active named stream (ref: RNGStatesTracker.rng_state)."""
    with _tracker.rng_state(name):
        yield


def add_rng_stream(name: str, seed_: int) -> None:
    _tracker.add(name, seed_)
