"""paddle.audio.features (ref: python/paddle/audio/features/layers.py —
Spectrogram:28, MelSpectrogram:110, LogMelSpectrogram:210, MFCC:313).

The frontend is matmul-shaped on purpose: STFT (batched rFFT via XLA) →
|·|^p → fbank matmul → dB/DCT matmul — each a fused XLA op on TPU."""

import jax.numpy as jnp

from paddle_tpu.nn.module import Module
from paddle_tpu import signal
from paddle_tpu.audio import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Module):
    """|STFT|^power over (..., T) waveforms → (..., freq, frames)."""

    def __init__(self, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=1.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        assert power > 0, "Power of spectrogram must be > 0."
        self.n_fft = n_fft
        self.hop_length = hop_length if hop_length is not None else n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = AF.get_window(window, self.win_length,
                                        fftbins=True, dtype=dtype)

    def forward(self, x):
        spec = signal.stft(jnp.asarray(x), self.n_fft, self.hop_length,
                           self.win_length, window=self.fft_window,
                           center=self.center, pad_mode=self.pad_mode)
        return jnp.abs(spec) ** self.power


class MelSpectrogram(Module):
    def __init__(self, sr=22050, n_fft=2048, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        spect = self._spectrogram(x)  # (..., freq, frames)
        return jnp.matmul(self.fbank_matrix, spect)


class LogMelSpectrogram(Module):
    def __init__(self, sr=22050, n_fft=2048, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self._melspectrogram(x),
                              ref_value=self.ref_value, amin=self.amin,
                              top_db=self.top_db)


class MFCC(Module):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=2048, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        assert n_mfcc <= n_mels, "n_mfcc cannot be larger than n_mels"
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = AF.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        mel = self._log_melspectrogram(x)  # (..., n_mels, frames)
        return jnp.matmul(jnp.swapaxes(self.dct_matrix, 0, 1), mel)
