"""Audio domain (ref: python/paddle/audio/__init__.py — features,
functional, datasets subpackages)."""

from paddle_tpu.audio import functional
from paddle_tpu.audio import features
from paddle_tpu.audio.features import (Spectrogram, MelSpectrogram,
                                       LogMelSpectrogram, MFCC)
from paddle_tpu.audio.functional import (hz_to_mel, mel_to_hz,
                                         mel_frequencies, fft_frequencies,
                                         compute_fbank_matrix, power_to_db,
                                         create_dct, get_window)
from paddle_tpu.audio.datasets import ESC50, TESS

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC", "hz_to_mel", "mel_to_hz",
           "mel_frequencies", "fft_frequencies", "compute_fbank_matrix",
           "power_to_db", "create_dct", "get_window", "ESC50", "TESS"]
