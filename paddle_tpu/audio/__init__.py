"""Audio domain (ref: python/paddle/audio/ — spectrograms, mel features)."""

import math

import numpy as np
import jax.numpy as jnp

from paddle_tpu import signal as pt_signal

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC",
           "mel_frequencies", "compute_fbank_matrix", "hz_to_mel",
           "mel_to_hz", "ESC50", "TESS"]


def hz_to_mel(freq):
    return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)


def mel_to_hz(mel):
    return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=8000.0):
    mels = np.linspace(hz_to_mel(f_min), hz_to_mel(f_max), n_mels)
    return mel_to_hz(mels)


def compute_fbank_matrix(sr=16000, n_fft=512, n_mels=64, f_min=0.0,
                         f_max=None):
    f_max = f_max or sr / 2
    freqs = np.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max)
    weights = np.zeros((n_mels, len(freqs)), np.float32)
    for i in range(n_mels):
        lower = (freqs - mel_f[i]) / max(mel_f[i + 1] - mel_f[i], 1e-5)
        upper = (mel_f[i + 2] - freqs) / max(mel_f[i + 2] - mel_f[i + 1],
                                             1e-5)
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    return jnp.asarray(weights)


class Spectrogram:
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect"):
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        n = self.win_length
        self.window = jnp.asarray(
            0.5 - 0.5 * np.cos(2 * math.pi * np.arange(n) / n)
            if window == "hann" else np.ones(n), jnp.float32)

    def __call__(self, x):
        spec = pt_signal.stft(jnp.asarray(x), self.n_fft, self.hop_length,
                              self.win_length, self.window,
                              center=self.center, pad_mode=self.pad_mode)
        return jnp.abs(spec) ** self.power


class MelSpectrogram:
    def __init__(self, sr=16000, n_fft=512, hop_length=None, n_mels=64,
                 f_min=0.0, f_max=None, **kwargs):
        self.spec = Spectrogram(n_fft, hop_length, **kwargs)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max)

    def __call__(self, x):
        s = self.spec(x)  # (..., freq, time)
        return jnp.einsum("mf,...ft->...mt", self.fbank, s)


class LogMelSpectrogram(MelSpectrogram):
    def __call__(self, x):
        return jnp.log10(jnp.maximum(super().__call__(x), 1e-10))


class MFCC:
    def __init__(self, sr=16000, n_mfcc=40, n_mels=64, **kwargs):
        self.logmel = LogMelSpectrogram(sr, n_mels=n_mels, **kwargs)
        n = n_mels
        k = np.arange(n_mfcc)[:, None]
        self.dct = jnp.asarray(
            np.cos(math.pi / n * (np.arange(n)[None, :] + 0.5) * k)
            * math.sqrt(2.0 / n), jnp.float32)

    def __call__(self, x):
        lm = self.logmel(x)
        return jnp.einsum("km,...mt->...kt", self.dct, lm)


from paddle_tpu.audio.datasets import ESC50, TESS  # noqa: E402
