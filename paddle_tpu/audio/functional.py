"""paddle.audio.functional (ref: python/paddle/audio/functional/
functional.py — hz_to_mel:23, mel_to_hz:79, mel_frequencies:124,
fft_frequencies:164, compute_fbank_matrix:187, power_to_db:260,
create_dct:304; window.py get_window). All jnp — the fbank/DCT matrices
are built once and the per-frame work is a matmul, which is exactly what
the MXU wants a feature frontend to be."""

import math

import jax.numpy as jnp

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk=False):
    """Hz → mel. Slaney by default (linear below 1 kHz, log above),
    HTK formula with htk=True (≙ functional.py:23)."""
    freq = jnp.asarray(freq, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + freq / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(freq >= min_log_hz,
                     min_log_mel + jnp.log(freq / min_log_hz) / logstep,
                     mels)


def mel_to_hz(mel, htk=False):
    mel = jnp.asarray(mel, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    return mel_to_hz(jnp.linspace(lo, hi, n_mels), htk).astype(dtype)


def fft_frequencies(sr, n_fft, dtype="float32"):
    return jnp.linspace(0, sr / 2.0, 1 + n_fft // 2).astype(dtype)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank (n_mels, 1 + n_fft//2)."""
    if f_max is None:
        f_max = sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return weights.astype(dtype)


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    s = jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """(n_mels, n_mfcc) type-II DCT matrix (≙ create_dct:304)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(1.0 / math.sqrt(2.0))
        dct = dct * math.sqrt(1.0 / (2.0 * n_mels))
    return dct.astype(dtype)


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window by name (≙ window.py get_window). Periodic (fftbins=True)
    windows for STFT."""
    m = win_length + 1 if fftbins else win_length
    n = jnp.arange(m, dtype=jnp.float32)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * n / (m - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * n / (m - 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * n / (m - 1))
             + 0.08 * jnp.cos(4 * math.pi * n / (m - 1)))
    elif window in ("boxcar", "rect", "rectangular", "ones"):
        w = jnp.ones((m,))
    elif window == "triang":
        w = 1.0 - jnp.abs((n - (m - 1) / 2.0) / ((m - 1) / 2.0))
    elif window == "bartlett":
        w = 1.0 - jnp.abs((2.0 * n - (m - 1)) / (m - 1))
    else:
        raise ValueError(f"unsupported window {window!r}")
    if fftbins:
        w = w[:-1]
    return w.astype(dtype)
