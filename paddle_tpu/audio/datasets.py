"""Audio datasets (ref: python/paddle/audio/datasets/ — TESS, ESC50,
GTZAN, UrbanSound8K). Downloads are environment-gated; synthetic mode
generates class-dependent harmonic waveforms (class k = fundamental
220*2^(k/12) Hz) so spectrogram classifiers can learn, keeping tests
hermetic."""

import os

import numpy as np

from paddle_tpu.io.dataset import Dataset

__all__ = ["ESC50", "TESS"]


class _SyntheticAudio(Dataset):
    SAMPLE_RATE = 16000

    def __init__(self, n_classes, mode="train", num_samples=200,
                 duration=1.0, seed=0, feature_fn=None):
        rs = np.random.RandomState(seed + (0 if mode == "train" else 1))
        t = np.arange(int(self.SAMPLE_RATE * duration)) / self.SAMPLE_RATE
        self.labels = rs.randint(0, n_classes, num_samples).astype(np.int64)
        waves = []
        for y in self.labels:
            f0 = 220.0 * 2.0 ** (y / 12.0)
            w = (np.sin(2 * np.pi * f0 * t)
                 + 0.5 * np.sin(2 * np.pi * 2 * f0 * t)
                 + 0.1 * rs.randn(len(t)))
            waves.append(w.astype(np.float32))
        self.waves = np.stack(waves)
        self.feature_fn = feature_fn

    def __getitem__(self, idx):
        w = self.waves[idx]
        if self.feature_fn is not None:
            w = np.asarray(self.feature_fn(w))
        return w, self.labels[idx]

    def __len__(self):
        return len(self.waves)


class ESC50(_SyntheticAudio):
    """Environmental sounds, 50 classes (ref audio/datasets/esc50.py).
    archive_path: optional real ESC-50 directory with audio/*.wav; absent
    → synthetic."""

    def __init__(self, mode="train", archive_path=None, feature_fn=None,
                 **kw):
        if archive_path is not None:
            raise NotImplementedError(
                "real ESC-50 loading needs an audio decoder (soundfile), "
                "unavailable in this image — omit archive_path for the "
                "synthetic split (never silently substituted)")
        super().__init__(50, mode=mode, feature_fn=feature_fn, **kw)


class TESS(_SyntheticAudio):
    """Toronto emotional speech, 7 classes (ref audio/datasets/tess.py)."""

    def __init__(self, mode="train", archive_path=None, feature_fn=None,
                 **kw):
        if archive_path is not None:
            raise NotImplementedError(
                "real TESS loading needs an audio decoder (soundfile), "
                "unavailable in this image — omit archive_path for the "
                "synthetic split (never silently substituted)")
        super().__init__(7, mode=mode, feature_fn=feature_fn, **kw)
