"""Deterministic fault-injection harness.

Reference analog: the reference hardens its runtime against worker death,
store partitions, and corrupted state (fleet elastic manager, dist_saver)
but ships no way to *provoke* those failures on demand; recovery paths go
untested until production trips them. This module is the missing half: a
process-global registry of fault rules that runtime code consults at named
**sites**. With no rules installed every site is a single boolean check —
the production hot path pays nothing.

Rules are selected by a deterministic per-site call index, never by a
random draw, so a fault plan replays identically run after run:

    rule fires on calls  ``after <= index < after + count``   (0-based)

Install rules three ways:

1. Context manager (unit tests)::

       from paddle_tpu.testing import faults
       with faults.inject("p2p.recv", "raise", exc="TimeoutError"):
           ...

2. Programmatic (scoped manually)::

       faults.install_rule("train.step", "kill", after=3)
       ...
       faults.clear()

3. Environment (subprocess / launch-CLI tests) — ``PT_FAULTS`` holds
   ``;``-separated rules, each ``site:action[:key=value[,key=value...]]``::

       PT_FAULTS="train.step:kill:after=3;store.get:delay:seconds=0.5"

   Workers call :func:`install_from_env` (the launch CLI's env contract
   propagates the variable untouched).

Actions:

    ``delay``     sleep ``seconds`` (default 0.1) before the op proceeds
    ``raise``     raise ``exc`` (TimeoutError | ConnectionError | OSError |
                  RuntimeError | BrokenPipeError; default TimeoutError)
    ``drop``      tell the caller to silently skip the op
                  (:func:`fire` returns ``"drop"``)
    ``kill``      ``os._exit(code)`` (default 1) — an abrupt worker death
                  the launcher / elastic layer must survive
    ``nan``       poison a float payload with NaN (:func:`transform` and
                  :func:`slot_mask` sites)
    ``bitflip``   flip bit ``bit`` of byte ``offset`` in a bytes payload
                  or a file (:func:`transform` / :func:`corrupt_file`)
    ``truncate``  cut a bytes payload / file to ``keep`` bytes (default
                  half its length)

Sites currently wired into the runtime:

    store.get             resilience.store_get (TCPStore reads)
    p2p.send / p2p.recv   distributed.p2p
    watchdog.enter        resilience.CollectiveWatchdog.guard
    collective.init       env.init_parallel_env
    ckpt.shard            checkpoint save (file corruption AFTER the
                          checksum is recorded — simulates disk rot that
                          verification must catch)
    ckpt.tmp_saved        AutoCheckpoint.save between shard write and
                          commit-rename (kill here orphans a .tmp dir)
    train.step            user training loops (see tests/_resume_worker.py)
                          and fleet.ElasticTrainer's epoch loop (the
                          chaos gate kills a trainer mid-step here)
    serve.loop            router.serve_replica's loop head — kill here
                          drops a serving replica mid-serve (the fleet
                          controller's chaos/heal gate)
    engine.poison_logits  DecodeEngine / PagedDecodeEngine (slot_mask)
    paged.shared_page     prefix-cache shared KV pages (transform)
    collective.quant_payload
                          quantized-collective wire blocks
                          (distributed/compression.py, :func:`spec`) —
                          consulted at TRACE time: the bitflip is baked
                          into the compiled step, so ``after=`` counts
                          traces, not executions
    redistribute.schedule
                          the in-HBM reshape pass
                          (distributed/redistribute.py): ``fire`` at
                          plan execution (kill/raise = a reshape that
                          dies mid-collective), ``transform`` on each
                          leaf's host buffer (bitflip/truncate that the
                          PT_RESHARD_VERIFY digest must catch) — every
                          action must degrade to the checkpoint
                          fallback, never corrupt train state
    drain.migrate         drain-time request migration
                          (router._migrate_open_requests): ``fire``
                          before each detach (kill/raise = a sender
                          dying mid-drain), ``transform`` on the
                          published KV blob (bitflip the wire digest
                          must catch) — failures fall back to
                          finish-in-place / handoff-failed re-place,
                          never a lost or corrupted stream
    store.partition       resilience.GuardedStore — consulted once per
                          op *attempt* (drop/raise = the op fails as if
                          the store were unreachable; a ``count=N`` rule
                          partitions N consecutive ops then heals;
                          delay = a slow store). Serve loops must
                          degrade to buffered results + missed
                          heartbeats, never replica suicide
    router.die            Router.poll head (kill = the coordinator
                          SIGKILLs itself mid-traffic; failover +
                          journal recovery must preserve every
                          request id — docs/fleet-ha.md)
    train.grad_poison     in-graph gradient corruption
                          (observability/numerics.py, :func:`spec`) —
                          nan/bitflip one leaf's grads inside the
                          jitted train step; ``layer=L`` targets one
                          layer of a stacked block (overlap scan body
                          or the (L, ...) leaf slice), ``key=`` substring
                          selects the leaf, ``step=S`` bakes an in-graph
                          step-counter gate (one compile, fires at
                          optimizer step S) — the localization drill the
                          numerics provenance header must name
"""

import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["inject", "install_rule", "install_from_env", "clear",
           "reset_counts", "enabled", "fire", "transform", "slot_mask",
           "spec", "corrupt_file", "Rule"]

_EXCEPTIONS = {
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "OSError": OSError,
    "RuntimeError": RuntimeError,
    "BrokenPipeError": BrokenPipeError,
}

_ACTIONS = ("delay", "raise", "drop", "kill", "nan", "bitflip", "truncate")

_lock = threading.Lock()
_rules: List["Rule"] = []
_counts: Dict[str, int] = {}
_enabled = False  # mirrored flag so disabled sites cost one attribute read


class Rule:
    """One fault rule: fires at ``site`` on call indices
    ``[after, after + count)``."""

    __slots__ = ("site", "action", "kw", "after", "count", "fired")

    def __init__(self, site: str, action: str, after: int = 0,
                 count: Optional[int] = None, **kw):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(one of {_ACTIONS})")
        self.site = site
        self.action = action
        self.after = int(after)
        self.count = None if count is None else int(count)
        self.kw = kw
        self.fired = 0

    def matches(self, site: str, index: int) -> bool:
        if site != self.site:
            return False
        if index < self.after:
            return False
        return self.count is None or index < self.after + self.count

    def __repr__(self):
        return (f"Rule({self.site}:{self.action} after={self.after} "
                f"count={self.count} {self.kw})")


def enabled() -> bool:
    """Cheap gate for hot paths: True iff any rule is installed."""
    return _enabled


def install_rule(site: str, action: str, **kw) -> Rule:
    global _enabled
    rule = Rule(site, action, **kw)
    with _lock:
        _rules.append(rule)
        _enabled = True
    return rule


def remove_rule(rule: Rule):
    global _enabled
    with _lock:
        if rule in _rules:
            _rules.remove(rule)
        _enabled = bool(_rules)


def clear():
    """Remove every rule and reset all per-site call counters."""
    global _enabled
    with _lock:
        del _rules[:]
        _counts.clear()
        _enabled = False


def reset_counts(site: Optional[str] = None):
    """Reset per-site call counters, keeping installed rules: all sites
    when ``site`` is None, else just that one. :class:`inject` resets
    only ITS site on entry (fresh=True default) so a fault plan replays
    identically however many injects ran before it — without rewinding
    the firing windows of rules installed for other sites."""
    with _lock:
        if site is None:
            _counts.clear()
        else:
            _counts.pop(site, None)


class inject:
    """Context manager installing one rule for the ``with`` body.

        with faults.inject("p2p.send", "drop", after=1, count=1):
            ...

    Entry resets the call counter of ITS site only (PR 4 footgun:
    ``after=`` silently counted calls from EARLIER inject blocks in the
    same test, so a second run of the same plan fired at different
    indices unless the test remembered to call ``clear()`` between
    runs). Each inject block therefore replays identically by
    construction, and a nested inject for a different site leaves the
    outer rule's firing window untouched. Pass ``fresh=False`` to opt
    out and keep accumulated indices — only meaningful when composing
    with rules installed via :func:`install_rule`, whose firing windows
    are anchored to the existing counters."""

    def __init__(self, site: str, action: str, fresh: bool = True,
                 **kw):
        self._args = (site, action, kw)
        self._fresh = fresh
        self._rule = None

    def __enter__(self) -> Rule:
        site, action, kw = self._args
        if self._fresh:
            reset_counts(site)
        self._rule = install_rule(site, action, **kw)
        return self._rule

    def __exit__(self, *exc):
        remove_rule(self._rule)
        return False


def install_from_env(env: Optional[Dict[str, str]] = None) -> int:
    """Parse ``PT_FAULTS`` and install its rules; returns how many."""
    spec = (env or os.environ).get("PT_FAULTS", "").strip()
    if not spec:
        return 0
    n = 0
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"PT_FAULTS rule {part!r}: want "
                             f"site:action[:k=v,...]")
        site, action = fields[0], fields[1]
        kw = {}
        if len(fields) > 2 and fields[2]:
            for item in fields[2].split(","):
                k, _, v = item.partition("=")
                try:
                    kw[k] = int(v)
                except ValueError:
                    try:
                        kw[k] = float(v)
                    except ValueError:
                        kw[k] = v
        install_rule(site, action, **kw)
        n += 1
    return n


def _next_index(site: str) -> int:
    with _lock:
        idx = _counts.get(site, 0)
        # traced callers (:func:`spec`) consume indices at TRACE time by
        # documented design — the counter itself is pure host state
        # ptlint: disable=PT003 -- host-side registry, trace-time by contract
        _counts[site] = idx + 1
        return idx


def _matching(site: str) -> List[Rule]:
    idx = _next_index(site)
    with _lock:
        hits = [r for r in _rules if r.matches(site, idx)]
        for r in hits:
            r.fired += 1
    return hits


def fire(site: str) -> Optional[str]:
    """Consult the plan at a control-flow site. May sleep, raise, or kill
    the process; returns ``"drop"`` when the caller should silently skip
    the guarded operation, else None."""
    if not _enabled:
        return None
    outcome = None
    for rule in _matching(site):
        act = rule.action
        if act == "delay":
            time.sleep(float(rule.kw.get("seconds", 0.1)))
        elif act == "raise":
            exc = _EXCEPTIONS.get(str(rule.kw.get("exc", "TimeoutError")),
                                  TimeoutError)
            raise exc(f"injected fault at {site!r}")
        elif act == "drop":
            outcome = "drop"
        elif act == "kill":
            os._exit(int(rule.kw.get("code", 1)))
        # payload actions are inert at control-flow sites
    return outcome


def transform(site: str, value):
    """Consult the plan at a payload site: returns ``value``, possibly
    corrupted (bytes: bitflip/truncate; float arrays: nan)."""
    if not _enabled:
        return value
    for rule in _matching(site):
        act = rule.action
        if act == "bitflip" and isinstance(value, (bytes, bytearray)):
            b = bytearray(value)
            if b:
                off = int(rule.kw.get("offset", len(b) // 2)) % len(b)
                b[off] ^= 1 << (int(rule.kw.get("bit", 0)) % 8)
            value = bytes(b)
        elif act == "truncate" and isinstance(value, (bytes, bytearray)):
            keep = int(rule.kw.get("keep", len(value) // 2))
            value = bytes(value[:keep])
        elif act == "nan":
            import numpy as np
            arr = np.array(value, copy=True)
            if arr.size and np.issubdtype(arr.dtype, np.floating):
                arr.reshape(-1)[:max(1, int(rule.kw.get("n", 1)))] = np.nan
            value = arr
        elif act == "delay":
            time.sleep(float(rule.kw.get("seconds", 0.1)))
        elif act == "raise":
            exc = _EXCEPTIONS.get(str(rule.kw.get("exc", "TimeoutError")),
                                  TimeoutError)
            raise exc(f"injected fault at {site!r}")
        elif act == "kill":
            os._exit(int(rule.kw.get("code", 1)))
    return value


def slot_mask(site: str, n: int):
    """Per-slot poison mask for batch engines: an (n,) bool numpy array,
    True for the slots a matching ``nan`` rule names (``slot=k`` or
    ``slots="0|2"``; no slot key → all). One call index per dispatch."""
    import numpy as np
    mask = np.zeros((n,), bool)
    if not _enabled:
        return mask
    for rule in _matching(site):
        if rule.action != "nan":
            continue
        if "slot" in rule.kw:
            mask[int(rule.kw["slot"]) % n] = True
        elif "slots" in rule.kw:
            for s in str(rule.kw["slots"]).split("|"):
                mask[int(s) % n] = True
        else:
            mask[:] = True
    return mask


def spec(site: str, actions=None) -> List[Dict]:
    """Consult the plan at an IN-GRAPH payload site: returns the kw dicts
    (plus ``"action"``) of matching rules instead of applying them — for
    sites inside traced/jitted code, where the payload is a tracer and the
    corruption must be expressed as graph ops (bit-xor on a bitcast) by
    the caller. Consumes one call index, like every other site; traced
    sites are consulted when the program is TRACED, so the rule fires per
    compilation, not per step (documented at ``collective.quant_payload``).
    ``actions`` optionally filters to a subset of rule actions."""
    if not _enabled:
        return []
    out = []
    for rule in _matching(site):
        if actions is None or rule.action in actions:
            out.append(dict(rule.kw, action=rule.action))
    return out


def corrupt_file(site: str, path: str):
    """File-corruption site: applies matching bitflip/truncate rules to
    the file at ``path`` in place (used by checkpoint save to simulate
    post-write disk corruption that verification must catch). Also a
    direct test helper: ``corrupt_file`` with a one-shot ``inject``."""
    if not _enabled or not os.path.exists(path):
        return
    for rule in _matching(site):
        if rule.action == "truncate":
            size = os.path.getsize(path)
            keep = int(rule.kw.get("keep", size // 2))
            with open(path, "r+b") as f:
                f.truncate(keep)
        elif rule.action == "bitflip":
            with open(path, "r+b") as f:
                data = bytearray(f.read())
                if data:
                    off = int(rule.kw.get("offset",
                                          len(data) // 2)) % len(data)
                    data[off] ^= 1 << (int(rule.kw.get("bit", 0)) % 8)
                    f.seek(0)
                    f.write(data)
        elif rule.action == "kill":
            os._exit(int(rule.kw.get("code", 1)))
