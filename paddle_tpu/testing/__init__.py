"""Test-support runtime: deterministic fault injection for the resilience
subsystem (``paddle_tpu.testing.faults``). Production code fires injection
sites that are no-ops unless a fault plan is installed, so every recovery
path in `distributed/resilience.py`, `distributed/checkpoint.py`, and the
serving engines has a deterministic test."""

from paddle_tpu.testing import faults

__all__ = ["faults"]
