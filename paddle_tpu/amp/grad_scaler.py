"""Dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py:26 GradScaler;
C++ ops operators/amp/{check_finite_and_unscale,update_loss_scaling}_op).

Functional: ``scale``/``unscale_and_check``/``update`` compose into the train
step so the whole thing compiles. In the hybrid-parallel case the found_inf
flag must be psum'd across mesh axes before the optimizer step (ref:
hybrid_parallel_optimizer.py:135-149); distributed.fleet wires that up.
"""

import jax
import jax.numpy as jnp

tree_map = jax.tree_util.tree_map


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self.enable = enable
        self.init_loss_scaling = init_loss_scaling
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n = decr_every_n_nan_or_inf
        self.use_dynamic = use_dynamic_loss_scaling
        # host-visible mirror for the eager-style API
        self._scale = jnp.asarray(init_loss_scaling, jnp.float32)
        self._good = jnp.zeros((), jnp.int32)
        self._bad = jnp.zeros((), jnp.int32)

    # -- functional API (use inside jit) --------------------------------------
    def init_state(self):
        return {"scale": jnp.asarray(self.init_loss_scaling, jnp.float32),
                "good": jnp.zeros((), jnp.int32),
                "bad": jnp.zeros((), jnp.int32)}

    def scale_loss(self, loss, state):
        if not self.enable:
            return loss
        return loss * state["scale"]

    def unscale_and_check(self, grads, state):
        """Returns (unscaled_grads, found_inf)."""
        if not self.enable:
            return grads, jnp.zeros((), jnp.bool_)
        inv = 1.0 / state["scale"]
        grads = tree_map(lambda g: g * inv, grads)
        leaves = jax.tree_util.tree_leaves(grads)
        found = jnp.zeros((), jnp.bool_)
        for g in leaves:
            found = found | ~jnp.all(jnp.isfinite(g))
        return grads, found

    def update_state(self, state, found_inf):
        if not self.enable or not self.use_dynamic:
            return state
        good = jnp.where(found_inf, 0, state["good"] + 1)
        bad = jnp.where(found_inf, state["bad"] + 1, 0)
        scale = state["scale"]
        scale = jnp.where(found_inf & (bad >= self.decr_every_n),
                          jnp.maximum(scale * self.decr_ratio, 1.0), scale)
        bad = jnp.where(bad >= self.decr_every_n, 0, bad)
        scale = jnp.where(~found_inf & (good >= self.incr_every_n_steps),
                          scale * self.incr_ratio, scale)
        good = jnp.where(good >= self.incr_every_n_steps, 0, good)
        return {"scale": scale, "good": good, "bad": bad}

    def apply_or_skip(self, new_params, new_opt_state, params, opt_state,
                      found_inf):
        """Select updated or original params depending on found_inf — every
        rank skips together once found_inf has been psum'd."""
        sel = lambda new, old: tree_map(
            lambda a, b: jnp.where(found_inf, b, a), new, old)
        return sel(new_params, params), sel(new_opt_state, opt_state)

    # -- eager-style parity API ------------------------------------------------
    def scale(self, loss):
        return loss * self._scale if self.enable else loss

    def unscale_(self, grads):
        state = {"scale": self._scale, "good": self._good, "bad": self._bad}
        grads, self._found = self.unscale_and_check(grads, state)
        return grads

    def update(self):
        state = {"scale": self._scale, "good": self._good, "bad": self._bad}
        state = self.update_state(state, getattr(self, "_found",
                                                 jnp.zeros((), jnp.bool_)))
        self._scale = state["scale"]
        self._good = state["good"]
        self._bad = state["bad"]

    def is_enable(self):
        return self.enable

    def get_loss_scaling(self):
        return float(self._scale)

    def state_dict(self):
        return {"scale": self._scale, "good": self._good, "bad": self._bad}

    def load_state_dict(self, d):
        self._scale = jnp.asarray(d["scale"])
        self._good = jnp.asarray(d["good"])
        self._bad = jnp.asarray(d["bad"])
