"""AMP autocast (ref: python/paddle/amp/auto_cast.py:21 decorate:83; op lists
in python/paddle/fluid/dygraph/amp/auto_cast.py WHITE_LIST:44 BLACK_LIST:55).

TPU-first policy: bf16 is the native fast dtype (no loss scaling needed, MXU
natively consumes bf16), so:

- O1 ≙ ``auto_cast(level='O1')``: inputs to matmul-class ops cast to bf16 via
  a context flag consulted by Linear/Conv/attention layers; reductions, norms
  and softmax-CE stay fp32 (the reference's black list).
- O2 ≙ ``decorate(model, level='O2')``: parameters cast to bf16 wholesale,
  master fp32 weights kept by the optimizer (multi_precision=True default).

fp16 with dynamic loss scaling (GradScaler) is provided for parity, but bf16
is the default on TPU.
"""

import contextlib
import threading

import jax.numpy as jnp

_state = threading.local()

# Reference O1 lists (fluid/dygraph/amp/auto_cast.py:44,55) adapted: names of
# our functional ops.
WHITE_LIST = {"matmul", "mm", "bmm", "einsum", "conv1d", "conv2d", "conv3d",
              "linear", "attention"}
BLACK_LIST = {"log", "exp", "mean", "sum", "cross_entropy", "softmax",
              "layer_norm", "batch_norm", "cosine_similarity", "norm"}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


def get_amp_dtype():
    return getattr(_state, "dtype", None)


def amp_enabled():
    return getattr(_state, "enabled", False)


def amp_cast(x, op_class="white"):
    """Called by layers on their inputs: casts to the amp dtype when inside
    an auto_cast region and the op class is white-listed."""
    dt = get_amp_dtype()
    if dt is None or op_class != "white":
        return x
    if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(dt)
    return x


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """ref: paddle.amp.auto_cast (amp/auto_cast.py:21)."""
    prev_dtype = getattr(_state, "dtype", None)
    prev_enabled = getattr(_state, "enabled", False)
    if enable:
        _state.dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
        _state.enabled = True
    try:
        yield
    finally:
        _state.dtype = prev_dtype
        _state.enabled = prev_enabled


autocast = auto_cast
amp_guard = auto_cast


def cast_model_to(model, dtype="bfloat16"):
    """Cast floating parameters of a Module (O2 path)."""
    return model.astype(dtype)


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """ref: paddle.amp.decorate (amp/auto_cast.py:83). O2: cast model params
    to bf16/fp16; master weights live in the optimizer (multi_precision)."""
    if level == "O2":
        if isinstance(models, (list, tuple)):
            models = type(models)(cast_model_to(m, dtype) for m in models)
        else:
            models = cast_model_to(models, dtype)
    if optimizers is None:
        return models
    return models, optimizers
