from paddle_tpu.amp.auto_cast import (auto_cast, autocast, decorate,
                                      amp_guard, white_list, black_list,
                                      get_amp_dtype, cast_model_to)
from paddle_tpu.amp.grad_scaler import GradScaler

__all__ = ["auto_cast", "autocast", "decorate", "GradScaler", "amp_guard",
           "white_list", "black_list", "get_amp_dtype", "cast_model_to"]
