"""Metrics (ref: python/paddle/metric/metrics.py — Metric base, Accuracy,
Precision, Recall, Auc)."""

import numpy as np
import jax.numpy as jnp

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1):  # noqa: A002
    """ref: paddle.metric.accuracy."""
    x = jnp.asarray(input)
    label = jnp.asarray(label)
    if label.ndim == x.ndim:
        label = label[..., 0]
    topk_idx = jnp.argsort(-x, axis=-1)[..., :k]
    correct = jnp.any(topk_idx == label[..., None], axis=-1)
    return jnp.mean(correct.astype(jnp.float32))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()

    def compute(self, pred, label):
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk))
        self.total = 0

    def compute(self, pred, label):
        pred = np.asarray(pred)
        label = np.asarray(label)
        if label.ndim == pred.ndim:
            label = label[..., 0]
        order = np.argsort(-pred, axis=-1)
        return order, label

    def update(self, correct_or_order, label=None):
        if label is None:
            # pre-computed correctness matrix
            c = np.asarray(correct_or_order)
            self.correct[0] += c.sum()
            self.total += c.shape[0]
            return c.mean()
        order = np.asarray(correct_or_order)
        label = np.asarray(label)
        for i, k in enumerate(self.topk):
            hit = (order[..., :k] == label[..., None]).any(-1)
            self.correct[i] += hit.sum()
        self.total += label.shape[0]
        return self.correct / max(self.total, 1)

    def accumulate(self):
        res = (self.correct / max(self.total, 1)).tolist()
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds) > 0.5
        labels = np.asarray(labels).astype(bool)
        self.tp += int(np.sum(preds & labels))
        self.fp += int(np.sum(preds & ~labels))

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds) > 0.5
        labels = np.asarray(labels).astype(bool)
        self.tp += int(np.sum(preds & labels))
        self.fn += int(np.sum(~preds & labels))

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming AUC via thresholded confusion bins (ref: metrics.py Auc /
    framework/fleet/metrics.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self.stat_pos = np.zeros(self.num_thresholds + 1)
        self.stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:
            preds = preds[:, -1]
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        for i, l in zip(idx, labels):
            if l:
                self.stat_pos[i] += 1
            else:
                self.stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self.stat_pos.sum()
        tot_neg = self.stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate from highest threshold down
        tp = np.cumsum(self.stat_pos[::-1])
        fp = np.cumsum(self.stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name
