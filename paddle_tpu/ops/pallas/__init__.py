"""Pallas TPU kernels — the framework's native-kernel layer.

Reference analog: paddle/fluid/operators/fused/ (110 hand-written CUDA
fusions, e.g. fused_attention_op.cu, fmha_ref.h) and the PHI kernel library's
GPU backends. On TPU the equivalent of a hand-written CUDA kernel is a Pallas
(Mosaic) kernel; everything else is left to XLA fusion.
"""

from paddle_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
