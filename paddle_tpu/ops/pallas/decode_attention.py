"""Flash-decode attention: single-query-per-sequence cached attention as a
Pallas TPU kernel.

Reference analog: the decode half of
paddle/fluid/operators/fused/fused_multi_transformer_op.cu (the
masked_multihead_attention CUDA path that reads the CacheKV tensor one
timestep at a time). The TPU re-design streams the KV cache block-wise
through VMEM with an online softmax, so one kernel launch covers the whole
cache read at HBM bandwidth:

- **Per-sequence lengths**: each batch row attends to its first
  ``lengths[b]`` cache entries. The lengths ride in as a scalar-prefetch
  operand and the KV BlockSpec index maps *clamp* trailing block indices to
  the row's last valid block — Mosaic's pipeline elides the DMA for a
  repeated block index, so blocks beyond a row's length cost no HBM
  traffic (``pl.when`` alone would only skip the compute, not the
  prefetch). That is what makes a continuous-batching engine with ragged
  lengths bandwidth-proportional: short sequences don't pay for the
  longest one.
- **GQA/MQA**: ``Hq % Hkv == 0``; all ``G = Hq // Hkv`` query heads of one
  KV head are processed together as the sublane dim of a single (G, block_k)
  MXU matmul, so grouped queries amortize each KV block read.
- **Head-major cache layout** ``(B, H, T, D)``: the kernel's KV block is a
  contiguous (block_k, D) tile — no transposition of the cache in HBM, the
  BlockSpec index map does the addressing.

Decode is forward-only (no VJP): generation never differentiates through
the cache.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["decode_attention", "decode_attention_reference"]

_LANES = 128
_NEG_INF = float("-inf")


def online_softmax_step(q, k, v, col0, length, acc_ref, m_ref, l_ref,
                        scale):
    """One KV-block update of the online softmax: masked scores against
    columns [col0, col0+block) valid below ``length``, then the running
    (m, l, acc) rescale-and-accumulate. Shared by the contiguous and
    the paged decode kernels — ONE numerics definition."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    col = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < length, s, _NEG_INF)

    m_prev = m_ref[...]
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    m_cur = jnp.maximum(m_cur, -1e30)  # fully-masked block → p = 0
    alpha = jnp.exp(m_prev - m_cur)
    p = jnp.exp(s - m_cur[:, :1])
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = (acc_ref[...] * alpha[:, :1]
                    + jax.lax.dot(p.astype(v.dtype), v,
                                  preferred_element_type=jnp.float32))
    m_ref[...] = m_cur


def online_softmax_init(acc_ref, m_ref, l_ref):
    m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)


def online_softmax_finalize(o_ref, acc_ref, l_ref):
    l = l_ref[:, :1]
    o_ref[0] = (acc_ref[...]
                / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def online_softmax_write_stats(ml_ref, m_ref, l_ref):
    """Pack the running (m, l) into the optional stats output: column 0
    = running max, column 1 = softmax denominator (columns 2+ are
    don't-care). ONE packing definition shared by the contiguous and
    paged decode kernels — the host-side unpack in both callers reads
    exactly these two columns."""
    l = l_ref[:, :1]
    ml_ref[0] = jnp.concatenate([m_ref[:, :1], l, l_ref[:, 2:]], axis=1)


def fold_fresh_row(o, m, l, q, k_row, v_row, scale, group):
    """Fold ONE extra KV column per row (its fresh k/v) into a decode
    kernel result obtained with ``return_stats``: the output equals a
    softmax over [prefix + fresh row], so the kernel only ever reads
    the existing prefix and the caches/pools stay READ-ONLY in the
    caller's layer loop. q (B, Hq, D); o/m/l from the kernel; k_row/
    v_row (B, Hkv, D) in cache dtype. Returns (B, Hq, D) float32. ONE
    numerics definition shared by the contiguous engine path
    (gpt.GPTBlock.decode_rows) and the paged engine. Zero-length rows
    are safe: l == 0 and m == -inf degrade to attention over just the
    fresh row."""
    b, hq, d = q.shape
    hkv = k_row.shape[1]
    qg = q.reshape(b, hkv, group, d)
    s_new = jnp.einsum("bhgd,bhd->bhg", qg.astype(jnp.float32),
                       k_row.astype(jnp.float32)) * scale
    s_new = s_new.reshape(b, hq)
    m2 = jnp.maximum(m, s_new)
    w_pre = l * jnp.exp(m - m2)
    w_new = jnp.exp(s_new - m2)
    v_exp = jnp.repeat(v_row.astype(jnp.float32), group, axis=1)
    return ((o.astype(jnp.float32) * w_pre[..., None]
             + v_exp * w_new[..., None])
            / (w_pre + w_new)[..., None])


def _kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *rest, scale, block_k,
            hkv, with_stats):
    # the stats output ref exists only when requested (out_specs are
    # built conditionally), so the trailing refs shift
    if with_stats:
        ml_ref, acc_ref, m_ref, l_ref = rest
    else:
        ml_ref, (acc_ref, m_ref, l_ref) = None, rest
    bh = pl.program_id(0)
    j = pl.program_id(1)
    nk = pl.num_programs(1)
    b = bh // hkv

    @pl.when(j == 0)
    def _init():
        online_softmax_init(acc_ref, m_ref, l_ref)

    length = len_ref[b]

    # Guard against double-counting: for j beyond the row's last valid
    # block the index map re-presents that SAME last block (to elide the
    # DMA), so the compute must not run again.
    @pl.when(j * block_k < length)
    def _body():
        online_softmax_step(q_ref[0], k_ref[0, 0], v_ref[0, 0],
                            j * block_k, length, acc_ref, m_ref, l_ref,
                            scale)

    @pl.when(j == nk - 1)
    def _finalize():
        online_softmax_finalize(o_ref, acc_ref, l_ref)
        if with_stats:
            online_softmax_write_stats(ml_ref, m_ref, l_ref)


def _pick_block(T: int, block_k: int) -> int:
    """Largest power-of-two lane-multiple block that divides T."""
    bk = min(block_k, T)
    while bk > _LANES and T % bk:
        bk //= 2
    if T % bk:
        raise ValueError(
            f"cache length {T} must be a multiple of {_LANES}")
    return bk


def decode_attention_reference(q, k_cache, v_cache, lengths, scale=None):
    """Naive XLA oracle: full masked softmax over the cache.

    q: (B, Hq, D); k/v_cache: (B, Hkv, T, D); lengths: (B,) int32.
    """
    b, hq, d = q.shape
    hkv, T = k_cache.shape[1], k_cache.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    group = hq // hkv
    qg = q.reshape(b, hkv, group, d)
    s = jnp.einsum("bhgd,bhtd->bhgt", qg, k_cache).astype(jnp.float32)
    s = s * scale
    mask = jnp.arange(T)[None, None, None, :] < lengths[:, None, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgt,bhtd->bhgd", p.astype(v_cache.dtype), v_cache)
    return o.reshape(b, hq, d).astype(q.dtype)


def decode_attention(q, k_cache, v_cache, lengths, scale=None,
                     block_k=512, interpret=None, return_stats=False):
    """One decode step of cached attention for B sequences at once.

    Args:
      q: (B, Hq, D) — the query for each sequence's current position.
      k_cache, v_cache: (B, Hkv, T, D) head-major caches with
        Hq % Hkv == 0 (GQA when Hkv < Hq). T must be a multiple of 128.
      lengths: (B,) int32 — row b attends to cache positions
        [0, lengths[b]); beyond-length blocks are not re-fetched from HBM
        (clamped scalar-prefetch index map).
      scale: softmax scale, default 1/sqrt(D).
      block_k: KV block size streamed through VMEM (shrunk to divide T).
      interpret: defaults to True off-TPU so tests run on CPU.
      return_stats: also return the online-softmax running max ``m`` and
        denominator ``l`` (each (B, Hq) f32) so the caller can fold
        extra attention columns in analytically — the decode engine
        adds the current token's fresh KV row this way, letting the
        kernel read ONLY the prefix.

    Returns (B, Hq, D) in q's dtype; with return_stats, (o, m, l).
    """
    q = jnp.asarray(q)
    k_cache, v_cache = jnp.asarray(k_cache), jnp.asarray(v_cache)
    b, hq, d = q.shape
    hkv, T = k_cache.shape[1], k_cache.shape[2]
    if hq % hkv:
        raise ValueError(f"GQA needs Hq % Hkv == 0, got {hq} vs {hkv}")
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    bk = _pick_block(T, block_k)
    nk = T // bk

    # all G query heads of one KV head ride the sublane dim of one matmul;
    # pad G up to the dtype's sublane tile
    sub = 16 if q.dtype in (jnp.bfloat16, jnp.float16) else 8
    gp = max(sub, (group + sub - 1) // sub * sub)
    qg = q.reshape(b, hkv, group, d).reshape(b * hkv, group, d)
    qg = jnp.pad(qg, ((0, 0), (0, gp - group), (0, 0)))

    def kv_index(bh, j, lens):
        # clamp past-the-end block indices to the last valid block: a
        # repeated index is not re-DMA'd, so rows shorter than T skip the
        # bandwidth for their tail
        bb = bh // hkv
        nb = jnp.maximum((lens[bb] + bk - 1) // bk, 1)
        return (bb, bh % hkv, jnp.minimum(j, nb - 1), 0)

    lengths = jnp.asarray(lengths, jnp.int32)
    out_specs = [pl.BlockSpec((1, gp, d), lambda bh, j, lens: (bh, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * hkv, gp, d), q.dtype)]
    if return_stats:  # stats output only exists when asked for — the
        # per-token serving hot path must not allocate a dead buffer
        out_specs.append(pl.BlockSpec((1, gp, _LANES),
                                      lambda bh, j, lens: (bh, 0, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * hkv, gp, _LANES), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * hkv, nk),
        in_specs=[
            pl.BlockSpec((1, gp, d), lambda bh, j, lens: (bh, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), kv_index),
            pl.BlockSpec((1, 1, bk, d), kv_index),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
        ],
    )
    res = pl.pallas_call(
        # ptlint: disable=PT001 -- scale is a static Python float kwarg
        # (a tracer here would already fail partial-binding)
        functools.partial(_kernel, scale=float(scale), block_k=bk,
                          hkv=hkv, with_stats=return_stats),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lengths, qg, k_cache, v_cache)
    o = res[0][:, :group, :].reshape(b, hq, d)
    if not return_stats:
        return o
    ml = res[1]
    m = ml[:, :group, 0].reshape(b, hq)
    l = ml[:, :group, 1].reshape(b, hq)
    return o, m, l


def ptgeom_cases():
    """Geometry registry for tools/ptgeom.py (ISSUE 20): bench-ladder
    cache shapes x the block_k sweep, under jax.eval_shape."""
    from paddle_tpu.analysis import kernelmodel as km

    def case(geom, block_k):
        p = km.LADDER[geom]
        d = p["dm"] // p["heads"]
        T = max(p["seq"], _LANES)
        B = 8
        q = km.sds((B, p["heads"], d), p["dtype"])
        kc = km.sds((B, p["kv_heads"], T, d), p["dtype"])
        ln = km.sds((B,), "int32")

        def run():
            import jax as _jax
            _jax.eval_shape(
                lambda q, kc, vc, ln: decode_attention(
                    q, kc, vc, ln, block_k=block_k),
                q, kc, kc, ln)
        return km.GeomCase(kernel="decode_attention", geometry=geom,
                           config=f"bk{block_k}", run=run)

    cases = [case("tiny", 512)]
    for geom in ("350m", "r06"):
        for bk in (256, 512, 1024):
            cases.append(case(geom, bk))
    return cases
