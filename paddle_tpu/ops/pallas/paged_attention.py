"""Paged flash-decode attention: block-table KV cache as a Pallas TPU
kernel (the vLLM-style serving memory model — no reference analog; the
reference's fused_multi_transformer serves one contiguous CacheKV per
sequence).

Why paged: a slot-contiguous cache must reserve max_len for every slot,
so HBM bounds in-flight sequences by the WORST length. A paged pool
shares fixed-size pages across sequences; a sequence holds
ceil(len/page) pages and frees them at retirement — memory scales with
the sum of actual lengths, not slots x max_len.

TPU mapping: the page table rides as a scalar-prefetch operand and the
KV BlockSpec index maps translate (sequence, block j) -> pool page id
at DMA-schedule time, so the kernel streams exactly the pages a
sequence owns — same online-softmax inner loop as decode_attention,
same clamp trick (a repeated page index is not re-fetched) for rows
shorter than the longest.

Two kernel entry points share one body:

- `paged_decode_attention` — read-only pools, optional (m, l) stats so
  the caller can fold extra columns analytically (the pre-fusion
  engine formulation).
- `paged_append_attend` — the FUSED append+attend step: the current
  token's fresh K/V row is folded into the online softmax *and*
  written into its pool page inside the kernel, with
  ``input_output_aliases`` on the pools so the write is in place. The
  one batched scatter per cache per token the engine used to pay
  disappears (ISSUE 6 / PAPERS "LLM Inference Acceleration via
  Efficient Operation Fusion").

Both take an autotunable ``(pages_per_program, head_block)`` config
(see `tune_paged_attention`): pages_per_program streams several pages
per grid step (separate BlockSpecs — pool pages are not contiguous, so
one bigger block cannot express this), head_block processes several
consecutive KV heads of one page per program (their rows ARE contiguous
in the head-major pool view). Both shrink the grid — the paged kernel's
measured overhead at short cache lengths is per-program dispatch over a
mostly-masked fixed-width table, not bandwidth.

Forward-only (generation never differentiates through the cache).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_decode_attention", "paged_decode_attention_reference",
           "paged_append_attend", "tune_paged_attention", "PagedKVCache"]

_LANES = 128
_NEG_INF = float("-inf")

# fallback when the autotune cache has no entry for the shape family:
# one page and one KV head per program (the pre-autotune geometry)
_DEFAULT_CONFIG = (1, 1)


def _tune_key(page, hkv, d, dtype, group, fused):
    from paddle_tpu.ops.pallas.autotune import AutotuneCache
    return AutotuneCache.key(
        "paged_append" if fused else "paged_attention",
        page=page, hkv=hkv, d=d, dtype=str(dtype), group=group)


def _resolve_config(ppp, hb, page, hkv, d, dtype, group, max_pages,
                    fused):
    """Fill unset config knobs from the autotune cache (trace-time dict
    read, ≙ flash_attention's block lookup) and clamp to validity:
    pages_per_program can't exceed the table width, head_block must
    divide Hkv."""
    if ppp is None or hb is None:
        from paddle_tpu.ops.pallas.autotune import get_cache
        hit = get_cache().get(_tune_key(page, hkv, d, dtype, group,
                                        fused))
        t_ppp, t_hb = hit if hit is not None else _DEFAULT_CONFIG
        ppp = t_ppp if ppp is None else ppp
        hb = t_hb if hb is None else hb
    # ptlint: disable=PT001 -- ppp/hb are static Python config knobs
    # (autotune-cache hits or explicit kwargs; a tracer here would
    # already have failed the cache lookup), never device values
    ppp = max(1, min(int(ppp), max_pages))
    hb = max(1, int(hb))  # ptlint: disable=PT001 -- static config knob
    while hkv % hb:
        hb -= 1
    return ppp, hb


def _kernel(*refs, scale, page, hkv, ppp, hb, with_stats, fused):
    # Ref layout (the table/wpid prefetch refs are consumed by the
    # BlockSpec index maps, not the body, but still appear in the ABI;
    # the stats output exists only when requested, so trailing refs
    # shift — same convention as the contiguous decode kernel):
    #   plain: len, table, q, k*ppp, v*ppp | o, [ml] | acc, m, l
    #   fused: len, table, wpid, q, k*ppp, v*ppp, krow, vrow, kwin,
    #          vwin | o, kw, vw | acc, m, l
    if fused:
        len_ref, _table_ref, _wpid_ref, q_ref = refs[:4]
        rest = refs[4:]
    else:
        len_ref, _table_ref, q_ref = refs[:3]
        rest = refs[3:]
    k_refs, v_refs = rest[:ppp], rest[ppp:2 * ppp]
    rest = rest[2 * ppp:]
    ml_ref = None
    if fused:
        (krow_ref, vrow_ref, kwin_ref, vwin_ref,
         o_ref, kw_ref, vw_ref, acc_ref, m_ref, l_ref) = rest
    elif with_stats:
        o_ref, ml_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    nhb = hkv // hb
    bh = pl.program_id(0)
    j = pl.program_id(1)
    b = bh // nhb

    from paddle_tpu.ops.pallas.decode_attention import (
        online_softmax_finalize, online_softmax_init,
        online_softmax_step, online_softmax_write_stats)

    @pl.when(j == 0)
    def _init():
        online_softmax_init(acc_ref, m_ref, l_ref)

    length = len_ref[b]

    # beyond the row's last valid page the index map re-presents that
    # SAME page (DMA elided); the compute must not run again
    for i in range(ppp):
        col0 = (j * ppp + i) * page

        @pl.when(col0 < length)
        def _body(i=i, col0=col0):
            for h in range(hb):
                online_softmax_step(q_ref[h], k_refs[i][h], v_refs[i][h],
                                    col0, length, acc_ref.at[h],
                                    m_ref.at[h], l_ref.at[h], scale)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        if fused:
            # fold the fresh row as one more single-column online step
            # (cols length..length+sub-1, only col==length unmasked —
            # the sublane-pad rows of krow score -inf), then merge it
            # into its pool page: the aliased write block is the page at
            # position length, row offset length % page replaced. The
            # write-back DMA lands after this grid row's last program —
            # the attend stream only ever read rows < length, so order
            # does not matter.
            off = length % page
            sel = jax.lax.broadcasted_iota(jnp.int32, (page, 1), 0) == off
            for h in range(hb):
                online_softmax_step(q_ref[h], krow_ref[h], vrow_ref[h],
                                    length, length + 1, acc_ref.at[h],
                                    m_ref.at[h], l_ref.at[h], scale)
                kw_ref[h] = jnp.where(sel, krow_ref[h][:1], kwin_ref[h])
                vw_ref[h] = jnp.where(sel, vrow_ref[h][:1], vwin_ref[h])
        for h in range(hb):
            # hb == 1 passes the block ref whole: a ``.at[0:1]`` view of
            # a size-1 dim is a "trivial" transform that jax 0.4.37's
            # interpret-mode discharge mishandles when stacked under the
            # helper's integer write
            ov = o_ref if hb == 1 else o_ref.at[h:h + 1]
            online_softmax_finalize(ov, acc_ref.at[h], l_ref.at[h])
            if with_stats:
                mlv = ml_ref if hb == 1 else ml_ref.at[h:h + 1]
                online_softmax_write_stats(mlv, m_ref.at[h],
                                           l_ref.at[h])


def paged_decode_attention_reference(q, k_pages, v_pages, page_table,
                                     lengths, scale=None):
    """XLA oracle: gather each row's pages contiguous, then full masked
    softmax. q: (B, Hq, D); pools (P, Hkv, page, D); page_table
    (B, max_pages) int32; lengths (B,)."""
    b, hq, d = q.shape
    hkv, page = k_pages.shape[1], k_pages.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # (B, max_pages, Hkv, page, D) -> (B, Hkv, max_pages*page, D)
    kg = jnp.swapaxes(k_pages[page_table], 1, 2)
    vg = jnp.swapaxes(v_pages[page_table], 1, 2)
    kc = kg.reshape(b, hkv, -1, d)
    vc = vg.reshape(b, hkv, -1, d)
    group = hq // hkv
    qg = q.reshape(b, hkv, group, d)
    s = jnp.einsum("bhgd,bhtd->bhgt", qg, kc).astype(jnp.float32) * scale
    T = kc.shape[2]
    mask = jnp.arange(T)[None, None, None, :] < lengths[:, None, None,
                                                        None]
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgt,bhtd->bhgd", p.astype(vc.dtype), vc)
    return o.reshape(b, hq, d).astype(q.dtype)


def _paged_call(q, k_pages, v_pages, page_table, lengths, scale,
                interpret, return_stats, pages_per_program, head_block,
                k_row=None, v_row=None, write_pids=None):
    """Shared call-site builder for the plain and fused paged kernels
    (fused ⇔ ``k_row`` is given)."""
    fused = k_row is not None
    q = jnp.asarray(q)
    k_pages, v_pages = jnp.asarray(k_pages), jnp.asarray(v_pages)
    b, hq, d = q.shape
    hkv, page = k_pages.shape[1], k_pages.shape[2]
    max_pages = page_table.shape[1]
    if hq % hkv:
        raise ValueError(f"GQA needs Hq % Hkv == 0, got {hq} vs {hkv}")
    if page % _LANES:
        raise ValueError(f"page_size {page} must be a multiple of "
                         f"{_LANES}")
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    ppp, hb = _resolve_config(pages_per_program, head_block, page, hkv,
                              d, q.dtype, group, max_pages, fused)
    nhb = hkv // hb
    nj = (max_pages + ppp - 1) // ppp

    sub = 16 if q.dtype in (jnp.bfloat16, jnp.float16) else 8
    gp = max(sub, (group + sub - 1) // sub * sub)
    qg = q.reshape(b * hkv, group, d)
    qg = jnp.pad(qg, ((0, 0), (0, gp - group), (0, 0)))

    lengths = jnp.asarray(lengths, jnp.int32)
    table_flat = jnp.asarray(page_table, jnp.int32).reshape(-1)
    # pools are indexed (page, head) -> (page, D): merge Hkv into the
    # leading dim via a head-major view so one block = hb consecutive
    # (page, D) tiles of one page. (P, Hkv, page, D) -> (P*Hkv, page, D)
    # with id p*Hkv+h; the hb-row block at p*nhb + head_block_index is
    # contiguous because heads vary fastest.
    kp = k_pages.reshape(-1, page, d)
    vp = v_pages.reshape(-1, page, d)

    def bh_index(bh, j, *pref):
        return (bh, 0, 0)

    def kv_index(i):
        def index(bh, j, lens, table, *maybe_wpid):
            bb = bh // nhb
            used = jnp.maximum((lens[bb] + page - 1) // page, 1)
            jj = jnp.minimum(j * ppp + i, used - 1)
            return (table[bb * max_pages + jj] * nhb + bh % nhb, 0, 0)
        return index

    in_specs = [pl.BlockSpec((hb, gp, d), bh_index)]
    in_specs += [pl.BlockSpec((hb, page, d), kv_index(i))
                 for i in range(ppp)]
    in_specs += [pl.BlockSpec((hb, page, d), kv_index(i))
                 for i in range(ppp)]
    out_specs = [pl.BlockSpec((hb, gp, d), bh_index)]
    out_shape = [jax.ShapeDtypeStruct((b * hkv, gp, d), q.dtype)]
    operands = [qg] + [kp] * ppp + [vp] * ppp

    if fused:
        def w_index(bh, j, lens, table, wpids):
            return (wpids[bh // nhb] * nhb + bh % nhb, 0, 0)

        krow = jnp.asarray(k_row).reshape(b * hkv, 1, d)
        vrow = jnp.asarray(v_row).reshape(b * hkv, 1, d)
        krow = jnp.pad(krow, ((0, 0), (0, sub - 1), (0, 0)))
        vrow = jnp.pad(vrow, ((0, 0), (0, sub - 1), (0, 0)))
        in_specs += [pl.BlockSpec((hb, sub, d), bh_index),
                     pl.BlockSpec((hb, sub, d), bh_index),
                     pl.BlockSpec((hb, page, d), w_index),
                     pl.BlockSpec((hb, page, d), w_index)]
        out_specs += [pl.BlockSpec((hb, page, d), w_index),
                      pl.BlockSpec((hb, page, d), w_index)]
        out_shape += [jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                      jax.ShapeDtypeStruct(vp.shape, vp.dtype)]
        operands += [krow, vrow, kp, vp]
        prefetch = (lengths, table_flat,
                    jnp.asarray(write_pids, jnp.int32))
        # the pool write-view operands alias the pool outputs: the
        # kernel's page write is in place, untouched pages keep their
        # input values. Operand numbering counts the scalar-prefetch
        # refs: 3 prefetch + q + 2*ppp streams + krow/vrow.
        aliases = {3 + 1 + 2 * ppp + 2: 1, 3 + 1 + 2 * ppp + 3: 2}
    else:
        if return_stats:  # stats output only exists when asked for
            out_specs.append(pl.BlockSpec((hb, gp, _LANES), bh_index))
            out_shape.append(
                jax.ShapeDtypeStruct((b * hkv, gp, _LANES), jnp.float32))
        prefetch = (lengths, table_flat)
        aliases = {}

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(b * nhb, nj),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((hb, gp, d), jnp.float32),
            pltpu.VMEM((hb, gp, _LANES), jnp.float32),
            pltpu.VMEM((hb, gp, _LANES), jnp.float32),
        ],
    )
    res = pl.pallas_call(
        # ptlint: disable=PT001 -- scale is a static Python float kwarg
        # (a tracer here would already fail partial-binding)
        functools.partial(_kernel, scale=float(scale), page=page,
                          hkv=hkv, ppp=ppp, hb=hb,
                          with_stats=return_stats, fused=fused),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*prefetch, *operands)
    o = res[0][:, :group, :].reshape(b, hq, d)
    if fused:
        kp_out = res[1].reshape(k_pages.shape)
        vp_out = res[2].reshape(v_pages.shape)
        return o, kp_out, vp_out
    if not return_stats:
        return o
    ml = res[1]
    m = ml[:, :group, 0].reshape(b, hq)
    l = ml[:, :group, 1].reshape(b, hq)
    return o, m, l


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths,
                           scale=None, interpret=None,
                           return_stats=False, pages_per_program=None,
                           head_block=None):
    """One decode step of cached attention over a PAGED KV pool.

    Args:
      q: (B, Hq, D) — each sequence's current-position query.
      k_pages, v_pages: (P, Hkv, page_size, D) shared page pools;
        page_size must be a multiple of 128.
      page_table: (B, max_pages) int32 — row b's i-th page id in the
        pool; entries beyond ceil(lengths[b]/page_size) are ignored.
      lengths: (B,) int32 — row b attends to its first lengths[b]
        tokens. Pages beyond a row's length are not fetched from HBM
        (clamped scalar-prefetch index map).
      scale: softmax scale, default 1/sqrt(D).
      interpret: defaults to True off-TPU so tests run on CPU.
      return_stats: also return the online-softmax running max ``m``
        and denominator ``l`` (each (B, Hq) f32) so the caller can
        fold extra attention columns in analytically — the paged
        engine's pre-fusion formulation added the current token's
        fresh KV row this way, keeping the pools READ-ONLY inside its
        layer scan.
      pages_per_program, head_block: kernel geometry; default (None)
        reads the autotune cache per (page, Hkv, D, dtype, group) key
        at trace time (`tune_paged_attention` fills it), falling back
        to (1, 1).

    Returns (B, Hq, D) in q's dtype; with return_stats, (o, m, l).
    """
    return _paged_call(q, k_pages, v_pages, page_table, lengths, scale,
                       interpret, return_stats, pages_per_program,
                       head_block)


def paged_append_attend(q, k_pages, v_pages, k_row, v_row, page_table,
                        write_pids, lengths, scale=None, interpret=None,
                        pages_per_program=None, head_block=None):
    """FUSED append+attend decode step over a paged KV pool.

    Attends each row over its prefix [0, lengths[b]) **plus** its fresh
    KV row (``k_row``/``v_row``, the current token's key/value — folded
    as one extra online-softmax column inside the kernel), and writes
    that fresh row into pool page ``write_pids[b]`` at row offset
    ``lengths[b] % page_size`` in the same kernel launch. The pools are
    input/output-aliased, so the write touches exactly one page per
    (row, KV-head) — the separate batched scatter per cache per token
    the paged engine previously dispatched is gone.

    Args:
      q: (B, Hq, D) current-position queries.
      k_pages, v_pages: (P, Hkv, page, D) pools (DONATED — aliased into
        the returned pools; do not reuse the inputs).
      k_row, v_row: (B, Hkv, D) fresh rows in pool dtype.
      page_table: (B, max_pages) int32 as in `paged_decode_attention`.
      write_pids: (B,) int32 — the pool page receiving row b's fresh KV
        (callers derive it from the block table + per-slot length, and
        point masked-out rows at a scratch page).
      lengths: (B,) int32 prefix lengths; the fresh row lands at
        position lengths[b].

    Returns (o, k_pages, v_pages): o (B, Hq, D) equals a softmax over
    [prefix + fresh row] (the fused analog of `fold_fresh_row`).
    """
    return _paged_call(q, k_pages, v_pages, page_table, lengths, scale,
                       interpret, False, pages_per_program, head_block,
                       k_row=k_row, v_row=v_row, write_pids=write_pids)


def tune_paged_attention(q, k_pages, v_pages, page_table, lengths,
                         scale=None, fused=True, candidates=None,
                         iters=3):
    """Eagerly measure paged-kernel geometry candidates on the REAL
    shapes and persist the winner (≙ flash_attention's
    tune_flash_attention; Pallas grids are trace-time constants, so
    tuning runs outside jit and later calls pick the tuned
    ``(pages_per_program, head_block)`` from the cache at trace time —
    warmup-compatible as long as tuning runs before the engine traces).

    Keyed per (page_size, Hkv, D, dtype, group) shape family — the
    knobs that set the kernel's per-program work — not per batch/table
    width, which only clamp the config. Returns (config, timings).
    """
    from paddle_tpu.ops.pallas import autotune as at

    q = jnp.asarray(q)
    k_pages = jnp.asarray(k_pages)
    b, hq, d = q.shape
    hkv, page = k_pages.shape[1], k_pages.shape[2]
    max_pages = page_table.shape[1]
    group = hq // hkv
    key = _tune_key(page, hkv, d, q.dtype, group, fused)
    if candidates is None:
        candidates = [(ppp, hb)
                      for ppp in (1, 2, 4) if ppp <= max_pages
                      for hb in (1, 2, 4) if hkv % hb == 0]
    if fused:
        k_row = jnp.zeros((b, hkv, d), k_pages.dtype)
        v_row = jnp.zeros((b, hkv, d), jnp.asarray(v_pages).dtype)
        wpids = jnp.asarray(page_table, jnp.int32)[:, 0]

    jitted = {}

    def build_and_run(cfg):
        if cfg not in jitted:
            ppp, hb = cfg
            if fused:
                def fn(q, kp, vp, table, lens, _ppp=ppp, _hb=hb):
                    o, kp2, vp2 = paged_append_attend(
                        q, kp, vp, k_row, v_row, table, wpids, lens,
                        scale=scale, pages_per_program=_ppp,
                        head_block=_hb)
                    return jnp.sum(o.astype(jnp.float32) ** 2)
            else:
                def fn(q, kp, vp, table, lens, _ppp=ppp, _hb=hb):
                    o = paged_decode_attention(
                        q, kp, vp, table, lens, scale=scale,
                        pages_per_program=_ppp, head_block=_hb)
                    return jnp.sum(o.astype(jnp.float32) ** 2)
            jitted[cfg] = jax.jit(fn)
        out = jitted[cfg](q, k_pages, v_pages, page_table, lengths)
        float(out)  # sync — the timing loop must see the kernel finish
    return at.tune("paged_attention", key, candidates, build_and_run,
                   iters=iters)


class PageAllocator:
    """LIFO free-list page allocator: the ONE reserve/release
    implementation shared by `PagedKVCache` and the paged serving
    engine."""

    def __init__(self, n_pages: int, page_size: int,
                 max_pages_per_seq: int = 0):
        self.page = int(page_size)
        self.n_pages = int(n_pages)
        self.max_pages = int(max_pages_per_seq)
        self._free = list(range(n_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def reserve(self, table, n_tokens):
        """Grow ``table`` (a list of page ids) to cover ``n_tokens``."""
        need = (n_tokens + self.page - 1) // self.page
        while len(table) < need:
            if not self._free:
                raise MemoryError("page pool exhausted")
            if self.max_pages and len(table) >= self.max_pages:
                raise MemoryError(
                    f"sequence exceeds max_pages_per_seq="
                    f"{self.max_pages}")
            table.append(self._free.pop())
        return table

    def release(self, table):
        self._free.extend(reversed(table))
        table.clear()


class PagedKVCache:
    """Host-side page pool + tables (the allocator half of paged
    serving; the kernel half is `paged_decode_attention`).

    One pool per model: k/v pages (P, Hkv, page, D) per layer stacked
    as (L, P, Hkv, page, D). Sequences allocate pages on demand and
    free them at retirement; `write_rows` places one decode step's new
    KV rows at each sequence's current position (page id + offset
    resolved host-side, written with per-sequence dynamic updates).
    """

    def __init__(self, n_layers, n_pages, kv_heads, page_size, head_dim,
                 dtype=jnp.bfloat16, max_pages_per_seq=None):
        if page_size % _LANES:
            raise ValueError(f"page_size {page_size} must be a multiple "
                             f"of {_LANES}")
        self.page = int(page_size)
        self.n_pages = int(n_pages)
        shape = (n_layers, n_pages, kv_heads, page_size, head_dim)
        self.kp = jnp.zeros(shape, dtype)
        self.vp = jnp.zeros(shape, dtype)
        self._alloc = PageAllocator(n_pages, page_size,
                                    max_pages_per_seq or 0)
        self.tables = {}        # seq id -> [page ids]
        self.lengths = {}       # seq id -> tokens written

    @property
    def free_pages(self):
        return self._alloc.free_pages

    def alloc_seq(self, seq_id, n_tokens=0):
        if seq_id in self.tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        self.tables[seq_id] = []
        self.lengths[seq_id] = 0
        if n_tokens:
            self.reserve(seq_id, n_tokens)

    def reserve(self, seq_id, n_tokens):
        """Ensure capacity for ``n_tokens`` total tokens."""
        self._alloc.reserve(self.tables[seq_id], n_tokens)

    def free_seq(self, seq_id):
        self._alloc.release(self.tables[seq_id])
        self.tables.pop(seq_id)
        self.lengths.pop(seq_id)

    def write_rows(self, seq_id, k_rows, v_rows):
        """Append one step's KV rows for every layer: k_rows/v_rows
        (L, Hkv, K, D) land at the sequence's current length. Writes
        go per touched PAGE RUN (rows within one page are contiguous),
        not per token — ceil(K/page)+1 updates instead of K."""
        K = k_rows.shape[2]
        pos = self.lengths[seq_id]
        self.reserve(seq_id, pos + K)
        tab = self.tables[seq_id]
        t = 0
        while t < K:
            pid = tab[(pos + t) // self.page]
            off = (pos + t) % self.page
            run = min(K - t, self.page - off)
            self.kp = jax.lax.dynamic_update_slice(
                self.kp, k_rows[:, None, :, t:t + run, :],
                (0, pid, 0, off, 0))
            self.vp = jax.lax.dynamic_update_slice(
                self.vp, v_rows[:, None, :, t:t + run, :],
                (0, pid, 0, off, 0))
            t += run
        self.lengths[seq_id] = pos + K

    def gather_args(self, seq_ids, layer):
        """(page_table, lengths) padded over ``seq_ids`` plus the
        layer's pools — the kernel-call operands for one layer."""
        import numpy as np
        mx = max(1, max(len(self.tables[s]) for s in seq_ids))
        table = np.zeros((len(seq_ids), mx), np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            tab = self.tables[s]
            table[i, :len(tab)] = tab
            lens[i] = self.lengths[s]
        return (jnp.asarray(table), jnp.asarray(lens),
                self.kp[layer], self.vp[layer])


def ptgeom_cases():
    """Geometry registry for tools/ptgeom.py (ISSUE 20): plain and
    fused paged decode across the (pages_per_program, head_block)
    autotune space, under jax.eval_shape."""
    from paddle_tpu.analysis import kernelmodel as km

    def case(geom, ppp, hb, fused):
        p = km.LADDER[geom]
        d = p["dm"] // p["heads"]
        hkv = p["kv_heads"]
        page = p["page"]
        B = 8
        mx = max(1, p["seq"] // page)
        q = km.sds((B, p["heads"], d), p["dtype"])
        pool = km.sds((B * mx + 1, hkv, page, d), p["dtype"])
        table = km.sds((B, mx), "int32")
        vec = km.sds((B,), "int32")
        row = km.sds((B, hkv, d), p["dtype"])

        def run():
            import jax as _jax
            if fused:
                _jax.eval_shape(
                    lambda q, kp, vp, kr, vr, tab, wp, ln:
                    paged_append_attend(q, kp, vp, kr, vr, tab, wp,
                                        ln, pages_per_program=ppp,
                                        head_block=hb),
                    q, pool, pool, row, row, table, vec, vec)
            else:
                _jax.eval_shape(
                    lambda q, kp, vp, tab, ln: paged_decode_attention(
                        q, kp, vp, tab, ln, pages_per_program=ppp,
                        head_block=hb),
                    q, pool, pool, table, vec)
        tag = "fused" if fused else "plain"
        return km.GeomCase(kernel=f"paged_{tag}", geometry=geom,
                           config=f"ppp{ppp}.hb{hb}", run=run)

    cases = [case("tiny", 1, 1, True)]
    for geom in ("350m", "r06"):
        for ppp, hb in ((1, 1), (2, 2), (4, 4)):
            cases.append(case(geom, ppp, hb, False))
        for ppp, hb in ((1, 1), (2, 2)):
            cases.append(case(geom, ppp, hb, True))
    return cases
