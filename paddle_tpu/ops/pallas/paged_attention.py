"""Paged flash-decode attention: block-table KV cache as a Pallas TPU
kernel (the vLLM-style serving memory model — no reference analog; the
reference's fused_multi_transformer serves one contiguous CacheKV per
sequence).

Why paged: a slot-contiguous cache must reserve max_len for every slot,
so HBM bounds in-flight sequences by the WORST length. A paged pool
shares fixed-size pages across sequences; a sequence holds
ceil(len/page) pages and frees them at retirement — memory scales with
the sum of actual lengths, not slots x max_len.

TPU mapping: the page table rides as a scalar-prefetch operand and the
KV BlockSpec index maps translate (sequence, block j) -> pool page id
at DMA-schedule time, so the kernel streams exactly the pages a
sequence owns — same online-softmax inner loop as decode_attention,
same clamp trick (a repeated page index is not re-fetched) for rows
shorter than the longest.

Forward-only (generation never differentiates through the cache).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_decode_attention", "paged_decode_attention_reference",
           "PagedKVCache"]

_LANES = 128
_NEG_INF = float("-inf")


def _kernel(len_ref, table_ref, q_ref, k_ref, v_ref, o_ref, *rest,
            scale, page, hkv, with_stats):
    # table_ref is consumed by the BlockSpec index maps (scalar
    # prefetch), not the body; it still appears in the kernel ABI.
    # The stats output ref exists only when requested (out_specs are
    # built conditionally), so the trailing refs shift — same
    # convention as the contiguous decode kernel.
    if with_stats:
        ml_ref, acc_ref, m_ref, l_ref = rest
    else:
        ml_ref, (acc_ref, m_ref, l_ref) = None, rest
    bh = pl.program_id(0)
    j = pl.program_id(1)
    b = bh // hkv

    from paddle_tpu.ops.pallas.decode_attention import (
        online_softmax_finalize, online_softmax_init,
        online_softmax_step, online_softmax_write_stats)

    @pl.when(j == 0)
    def _init():
        online_softmax_init(acc_ref, m_ref, l_ref)

    length = len_ref[b]

    # beyond the row's last valid page the index map re-presents that
    # SAME page (DMA elided); the compute must not run again
    @pl.when(j * page < length)
    def _body():
        online_softmax_step(q_ref[0], k_ref[0], v_ref[0], j * page,
                            length, acc_ref, m_ref, l_ref, scale)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        online_softmax_finalize(o_ref, acc_ref, l_ref)
        if with_stats:
            online_softmax_write_stats(ml_ref, m_ref, l_ref)


def paged_decode_attention_reference(q, k_pages, v_pages, page_table,
                                     lengths, scale=None):
    """XLA oracle: gather each row's pages contiguous, then full masked
    softmax. q: (B, Hq, D); pools (P, Hkv, page, D); page_table
    (B, max_pages) int32; lengths (B,)."""
    b, hq, d = q.shape
    hkv, page = k_pages.shape[1], k_pages.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # (B, max_pages, Hkv, page, D) -> (B, Hkv, max_pages*page, D)
    kg = jnp.swapaxes(k_pages[page_table], 1, 2)
    vg = jnp.swapaxes(v_pages[page_table], 1, 2)
    kc = kg.reshape(b, hkv, -1, d)
    vc = vg.reshape(b, hkv, -1, d)
    group = hq // hkv
    qg = q.reshape(b, hkv, group, d)
    s = jnp.einsum("bhgd,bhtd->bhgt", qg, kc).astype(jnp.float32) * scale
    T = kc.shape[2]
    mask = jnp.arange(T)[None, None, None, :] < lengths[:, None, None,
                                                        None]
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgt,bhtd->bhgd", p.astype(vc.dtype), vc)
    return o.reshape(b, hq, d).astype(q.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths,
                           scale=None, interpret=None,
                           return_stats=False):
    """One decode step of cached attention over a PAGED KV pool.

    Args:
      q: (B, Hq, D) — each sequence's current-position query.
      k_pages, v_pages: (P, Hkv, page_size, D) shared page pools;
        page_size must be a multiple of 128.
      page_table: (B, max_pages) int32 — row b's i-th page id in the
        pool; entries beyond ceil(lengths[b]/page_size) are ignored.
      lengths: (B,) int32 — row b attends to its first lengths[b]
        tokens. Pages beyond a row's length are not fetched from HBM
        (clamped scalar-prefetch index map).
      scale: softmax scale, default 1/sqrt(D).
      interpret: defaults to True off-TPU so tests run on CPU.
      return_stats: also return the online-softmax running max ``m``
        and denominator ``l`` (each (B, Hq) f32) so the caller can
        fold extra attention columns in analytically — the paged
        engine adds the current token's fresh KV row this way, keeping
        the pools READ-ONLY inside its layer scan.

    Returns (B, Hq, D) in q's dtype; with return_stats, (o, m, l).
    """
    q = jnp.asarray(q)
    k_pages, v_pages = jnp.asarray(k_pages), jnp.asarray(v_pages)
    b, hq, d = q.shape
    hkv, page = k_pages.shape[1], k_pages.shape[2]
    max_pages = page_table.shape[1]
    if hq % hkv:
        raise ValueError(f"GQA needs Hq % Hkv == 0, got {hq} vs {hkv}")
    if page % _LANES:
        raise ValueError(f"page_size {page} must be a multiple of "
                         f"{_LANES}")
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    sub = 16 if q.dtype in (jnp.bfloat16, jnp.float16) else 8
    gp = max(sub, (group + sub - 1) // sub * sub)
    qg = q.reshape(b * hkv, group, d)
    qg = jnp.pad(qg, ((0, 0), (0, gp - group), (0, 0)))

    def kv_index(bh, j, lens, table):
        bb = bh // hkv
        used = jnp.maximum((lens[bb] + page - 1) // page, 1)
        jj = jnp.minimum(j, used - 1)
        return (table[bb * max_pages + jj], bh % hkv, 0, 0)

    lengths = jnp.asarray(lengths, jnp.int32)
    table_flat = jnp.asarray(page_table, jnp.int32).reshape(-1)
    # pools are indexed (page, head) -> (page, D): merge Hkv into the
    # leading dim via a head-major view so one block = one (page, D)
    # tile. (P, Hkv, page, D) -> (P*Hkv, page, D) with id p*Hkv+h.
    kp = k_pages.reshape(-1, page, d)
    vp = v_pages.reshape(-1, page, d)

    def kv_index_flat(bh, j, lens, table):
        p, h, _, _ = kv_index(bh, j, lens, table)
        return (p * hkv + h, 0, 0)

    out_specs = [pl.BlockSpec((1, gp, d), lambda bh, j, lens, table:
                              (bh, 0, 0))]
    out_shape = [jax.ShapeDtypeStruct((b * hkv, gp, d), q.dtype)]
    if return_stats:  # stats output only exists when asked for
        out_specs.append(pl.BlockSpec((1, gp, _LANES),
                                      lambda bh, j, lens, table:
                                      (bh, 0, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b * hkv, gp, _LANES), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * hkv, max_pages),
        in_specs=[
            pl.BlockSpec((1, gp, d), lambda bh, j, lens, table:
                         (bh, 0, 0)),
            pl.BlockSpec((1, page, d), kv_index_flat),
            pl.BlockSpec((1, page, d), kv_index_flat),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
        ],
    )
    res = pl.pallas_call(
        # ptlint: disable=PT001 -- scale is a static Python float kwarg
        # (a tracer here would already fail partial-binding)
        functools.partial(_kernel, scale=float(scale), page=page,
                          hkv=hkv, with_stats=return_stats),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lengths, table_flat, qg, kp, vp)
    o = res[0][:, :group, :].reshape(b, hq, d)
    if not return_stats:
        return o
    ml = res[1]
    m = ml[:, :group, 0].reshape(b, hq)
    l = ml[:, :group, 1].reshape(b, hq)
    return o, m, l


class PageAllocator:
    """LIFO free-list page allocator: the ONE reserve/release
    implementation shared by `PagedKVCache` and the paged serving
    engine."""

    def __init__(self, n_pages: int, page_size: int,
                 max_pages_per_seq: int = 0):
        self.page = int(page_size)
        self.n_pages = int(n_pages)
        self.max_pages = int(max_pages_per_seq)
        self._free = list(range(n_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def reserve(self, table, n_tokens):
        """Grow ``table`` (a list of page ids) to cover ``n_tokens``."""
        need = (n_tokens + self.page - 1) // self.page
        while len(table) < need:
            if not self._free:
                raise MemoryError("page pool exhausted")
            if self.max_pages and len(table) >= self.max_pages:
                raise MemoryError(
                    f"sequence exceeds max_pages_per_seq="
                    f"{self.max_pages}")
            table.append(self._free.pop())
        return table

    def release(self, table):
        self._free.extend(reversed(table))
        table.clear()


class PagedKVCache:
    """Host-side page pool + tables (the allocator half of paged
    serving; the kernel half is `paged_decode_attention`).

    One pool per model: k/v pages (P, Hkv, page, D) per layer stacked
    as (L, P, Hkv, page, D). Sequences allocate pages on demand and
    free them at retirement; `write_rows` places one decode step's new
    KV rows at each sequence's current position (page id + offset
    resolved host-side, written with per-sequence dynamic updates).
    """

    def __init__(self, n_layers, n_pages, kv_heads, page_size, head_dim,
                 dtype=jnp.bfloat16, max_pages_per_seq=None):
        if page_size % _LANES:
            raise ValueError(f"page_size {page_size} must be a multiple "
                             f"of {_LANES}")
        self.page = int(page_size)
        self.n_pages = int(n_pages)
        shape = (n_layers, n_pages, kv_heads, page_size, head_dim)
        self.kp = jnp.zeros(shape, dtype)
        self.vp = jnp.zeros(shape, dtype)
        self._alloc = PageAllocator(n_pages, page_size,
                                    max_pages_per_seq or 0)
        self.tables = {}        # seq id -> [page ids]
        self.lengths = {}       # seq id -> tokens written

    @property
    def free_pages(self):
        return self._alloc.free_pages

    def alloc_seq(self, seq_id, n_tokens=0):
        if seq_id in self.tables:
            raise ValueError(f"sequence {seq_id} already allocated")
        self.tables[seq_id] = []
        self.lengths[seq_id] = 0
        if n_tokens:
            self.reserve(seq_id, n_tokens)

    def reserve(self, seq_id, n_tokens):
        """Ensure capacity for ``n_tokens`` total tokens."""
        self._alloc.reserve(self.tables[seq_id], n_tokens)

    def free_seq(self, seq_id):
        self._alloc.release(self.tables[seq_id])
        self.tables.pop(seq_id)
        self.lengths.pop(seq_id)

    def write_rows(self, seq_id, k_rows, v_rows):
        """Append one step's KV rows for every layer: k_rows/v_rows
        (L, Hkv, K, D) land at the sequence's current length. Writes
        go per touched PAGE RUN (rows within one page are contiguous),
        not per token — ceil(K/page)+1 updates instead of K."""
        K = k_rows.shape[2]
        pos = self.lengths[seq_id]
        self.reserve(seq_id, pos + K)
        tab = self.tables[seq_id]
        t = 0
        while t < K:
            pid = tab[(pos + t) // self.page]
            off = (pos + t) % self.page
            run = min(K - t, self.page - off)
            self.kp = jax.lax.dynamic_update_slice(
                self.kp, k_rows[:, None, :, t:t + run, :],
                (0, pid, 0, off, 0))
            self.vp = jax.lax.dynamic_update_slice(
                self.vp, v_rows[:, None, :, t:t + run, :],
                (0, pid, 0, off, 0))
            t += run
        self.lengths[seq_id] = pos + K

    def gather_args(self, seq_ids, layer):
        """(page_table, lengths) padded over ``seq_ids`` plus the
        layer's pools — the kernel-call operands for one layer."""
        import numpy as np
        mx = max(1, max(len(self.tables[s]) for s in seq_ids))
        table = np.zeros((len(seq_ids), mx), np.int32)
        lens = np.zeros((len(seq_ids),), np.int32)
        for i, s in enumerate(seq_ids):
            tab = self.tables[s]
            table[i, :len(tab)] = tab
            lens[i] = self.lengths[s]
        return (jnp.asarray(table), jnp.asarray(lens),
                self.kp[layer], self.vp[layer])
