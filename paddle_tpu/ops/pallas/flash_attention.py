"""Flash attention (forward + backward) as Pallas TPU kernels.

Reference analog: paddle/fluid/operators/fused/fused_attention_op.cu and
fmha_ref.h (cuDNN/hand-CUDA fused attention). This is the TPU-native
re-design: an online-softmax (FlashAttention-2 style) kernel tiled for the
MXU, with a custom VJP whose backward recomputes attention probabilities
from the saved log-sum-exp instead of materializing the (S, S) matrix.

Layout contract: public API takes (B, S, H, D) like
paddle.nn.functional.scaled_dot_product_attention; kernels operate on
(B*H, S, D). Sequence and head dims are zero-padded to tile multiples; KV
padding is masked inside the kernel, Q padding is sliced off (its gradient
contributions vanish because the padded dO rows are zero).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_LANES = 128
_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# Forward kernel: grid (BH, nq, nk); nk is the innermost "arbitrary" dim with
# running (m, l, acc) scratch carried across kv blocks.
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal, scale, sk_valid, block_q, block_k):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Causal: blocks strictly above the diagonal contribute nothing.
    run = (j * block_k <= (i + 1) * block_q - 1) if causal else (j >= 0)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < sk_valid
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, :1])
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha[:, :1]
                        + jax.lax.dot(p.astype(v.dtype), v,
                                      preferred_element_type=jnp.float32))
        m_ref[...] = m_cur

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # lane-broadcast (block_q, 128) layout: Mosaic requires the last two
        # block dims to be (8k, 128m); a (1, block_q) row block is rejected
        lse_ref[0] = m_ref[...] + jnp.log(l_ref[...])


def _fa_forward(q, k, v, causal, scale, sk_valid, block_q, block_k,
                interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, sk_valid=sk_valid,
        block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels. dK/dV: grid (BH, nk, nq) accumulating over q blocks.
# dQ: grid (BH, nq, nk) accumulating over kv blocks. Probabilities are
# recomputed from the saved LSE; delta = rowsum(dO * O) is precomputed.
# ---------------------------------------------------------------------------


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_acc, dv_acc,
                     *, causal, scale, sk_valid, block_q, block_k):
    j = pl.program_id(1)
    i = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = ((i + 1) * block_q - 1 >= j * block_k) if causal else (i >= 0)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < sk_valid
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc,
                   *, causal, scale, sk_valid, block_q, block_k):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = (j * block_k <= (i + 1) * block_q - 1) if causal else (j >= 0)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = col < sk_valid
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_acc[...] += jax.lax.dot(ds, k,
                                   preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_backward(q, k, v, out, lse, do, causal, scale, sk_valid, block_q,
                 block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True), (bh, sq, _LANES))

    kw = dict(causal=causal, scale=scale, sk_valid=sk_valid,
              block_q=block_q, block_k=block_k)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    rowspec = pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, **kw),
        grid=(bh, nk, nq),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    qspec2 = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    rowspec2 = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw),
        grid=(bh, nq, nk),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[qspec2],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)[0]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wiring on the padded (BH, S, D) representation
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, sk_valid, block_q, block_k, interpret):
    out, _ = _fa_forward(q, k, v, causal, scale, sk_valid, block_q, block_k,
                         interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, sk_valid, block_q, block_k,
               interpret):
    out, lse = _fa_forward(q, k, v, causal, scale, sk_valid, block_q,
                           block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, sk_valid, block_q, block_k, interpret,
               residuals, do):
    q, k, v, out, lse = residuals
    return _fa_backward(q, k, v, out, lse, do, causal, scale, sk_valid,
                        block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=256,
                    block_k=512, interpret=None):
    """Flash attention over (B, S, H, D) inputs; returns (B, S, H, D).

    ``causal=True`` requires equal Q/KV sequence lengths (self-attention).
    ``interpret`` defaults to True off-TPU so tests run on CPU.
    Default blocks (256, 512) measured 1.48x over the XLA reference path at
    (8, 2048, 16, 64) bf16 fwd+bwd on a v5e chip; (128, 128) was 0.5x.
    """
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if causal and sq != sk:
        raise ValueError(
            f"causal flash attention needs sq == sk, got {sq} vs {sk}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    # clamp blocks for short sequences — padding 128 rows up to a 256/512
    # block would multiply the real work
    block_q = min(block_q, _round_up(sq, _LANES))
    block_k = min(block_k, _round_up(sk, _LANES))
    sq_p = _round_up(max(sq, block_q), block_q)
    sk_p = _round_up(max(sk, block_k), block_k)
    # D is NOT padded: Mosaic accepts a block dim equal to the full array
    # dim, and zero-padding 64→128 would double the contraction FLOPs.

    def to3(x, s_p):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, x.shape[1], d)
        return jnp.pad(x, ((0, 0), (0, s_p - x.shape[1]), (0, 0)))

    out3 = _flash(to3(q, sq_p), to3(k, sk_p), to3(v, sk_p), causal,
                  float(scale), sk, block_q, block_k, bool(interpret))
    out = out3[:, :sq, :].reshape(b, h, sq, d)
    return jnp.transpose(out, (0, 2, 1, 3))
