"""Flash attention (forward + backward) as Pallas TPU kernels.

Reference analog: paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h (dropout), fused_softmax_mask.cu.h (mask fusion). This is the
TPU-native re-design: an online-softmax (FlashAttention-2 style) kernel
tiled for the MXU, with a custom VJP whose backward recomputes attention
probabilities from the saved log-sum-exp instead of materializing the
(S, S) matrix.

v2 capabilities (VERDICT r2 item 3):
- **Key-padding masks** via per-example ``kv_lens`` (the BERT path): each
  batch row attends to its first ``kv_lens[b]`` keys; fully-masked KV
  blocks are skipped, not just masked.
- **Additive bias** of shape (B|1, H|1, Sq, Sk) (e.g. relative-position or
  arbitrary additive masks), blocked into the kernel without materializing
  a (B, H, Sq, Sk) tensor when a broadcast dim is 1. The bias is treated
  as a constant: its cotangent is zero (use the XLA reference path to
  train through a bias).
- **Deterministic dropout** on the attention probabilities from an explicit
  integer seed: the keep-mask is a counter-based hash PRF of
  (head, row, col, seed), so forward and backward regenerate identical
  masks with zero residual memory (≙ fmha_ref.h's Philox dropout).
- **GQA**: ``k``/``v`` may carry fewer heads than ``q`` (Hq % Hkv == 0);
  query head h reads kv head h // (Hq // Hkv).

Layout contract: public API takes (B, S, H, D) like
paddle.nn.functional.scaled_dot_product_attention; kernels operate on
(B*H, S, D). Sequence dims are zero-padded to tile multiples; KV padding
is masked inside the kernel, Q padding is sliced off (its gradient
contributions vanish because the padded dO rows are zero).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

_LANES = 128
_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _keep_mask(seed, bh, i, j, block_q, block_k, sk_total, rate):
    """Counter-based keep mask: lowbias32 hash of the global (row, col)
    cell index mixed with (seed, head). Deterministic across fwd/bwd."""

    def mix(x):
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x7FEB352D)
        x = x ^ (x >> 15)
        x = x * jnp.uint32(0x846CA68B)
        return x ^ (x >> 16)

    row = (i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)).astype(jnp.uint32)
    col = (j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)).astype(jnp.uint32)
    lin = row * jnp.uint32(sk_total) + col
    h = mix(mix(lin ^ seed.astype(jnp.uint32)) ^ bh.astype(jnp.uint32))
    thresh = jnp.uint32(min(int(rate * 2.0**32), 2**32 - 1))
    return h >= thresh


def _mask_cols(s, kvlen, i, j, block_q, block_k, causal):
    col = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = col < kvlen
    if causal:
        row = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(mask, row >= col)
    return jnp.where(mask, s, _NEG_INF)


# ---------------------------------------------------------------------------
# Forward kernel: grid (BH, nq, nk); nk is the innermost "arbitrary" dim with
# running (m, l, acc) scratch carried across kv blocks.
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, causal, scale, block_q, block_k, has_bias,
                bias_sq1, dropout_rate, sk_total):
    kvlen_ref, seed_ref, q_ref, k_ref, v_ref = refs[:5]
    idx = 5
    bias_ref = refs[idx] if has_bias else None
    idx += int(has_bias)
    o_ref, lse_ref, acc_ref, m_ref, l_ref = refs[idx:idx + 5]

    bh = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    kvlen = kvlen_ref[bh]
    # Causal: blocks strictly above the diagonal contribute nothing.
    # KV blocks entirely beyond this row's valid length are skipped.
    run = jnp.logical_and(
        (j * block_k <= (i + 1) * block_q - 1) if causal else (j >= 0),
        j * block_k < kvlen)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        s = _mask_cols(s, kvlen, i, j, block_q, block_k, causal)

        m_prev = m_ref[...]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # finite floor: a block whose every cell is masked (-inf bias)
        # must give p = exp(-inf - m_cur) = 0, not exp(-inf + inf) = NaN
        m_cur = jnp.maximum(m_cur, -1e30)
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, :1])
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], bh, i, j, block_q, block_k,
                              sk_total, dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        acc_ref[...] = (acc_ref[...] * alpha[:, :1]
                        + jax.lax.dot(p.astype(v.dtype), v,
                                      preferred_element_type=jnp.float32))
        m_ref[...] = m_cur

    @pl.when(j == nk - 1)
    def _finalize():
        # rows with zero valid keys (kvlen == 0) produce 0 output and a
        # finite lse so the backward recomputation stays NaN-free
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        # lane-broadcast (block_q, 128) layout: Mosaic requires the last two
        # block dims to be (8k, 128m); a (1, block_q) row block is rejected
        m_safe = jnp.where(m_ref[...] == _NEG_INF, 0.0, m_ref[...])
        lse_ref[0] = m_safe + jnp.log(jnp.where(l_ref[...] == 0.0, 1.0,
                                                l_ref[...]))


def _bias_group(bias_mode, h_q):
    """Index map component selecting the bias leading dim from the bh grid
    index, for bias collapsed to (G, Sq|1, Sk)."""
    if bias_mode == "one":
        return lambda b: 0
    if bias_mode == "batch":
        return lambda b: b // h_q
    if bias_mode == "head":
        return lambda b: b % h_q
    return lambda b: b  # "bh"


def _bias_spec(bias_sq1, block_q, block_k, g, grid_ij):
    """Bias BlockSpec: a size-1 Sq dim stays size-1 (index map pins it to
    block 0) so a key-only mask is never broadcast to (..., Sq, Sk) in HBM;
    the kernel's `s + bias` broadcasts it across rows for free."""
    bq = 1 if bias_sq1 else block_q
    if grid_ij:  # grid (b, i, j)
        return pl.BlockSpec(
            (1, bq, block_k),
            lambda b, i, j: (g(b), 0 if bias_sq1 else i, j))
    # grid (b, j, i) — the dk/dv pass
    return pl.BlockSpec(
        (1, bq, block_k),
        lambda b, j, i: (g(b), 0 if bias_sq1 else i, j))


def _fa_forward(q, k, v, kvlen, seed, bias, causal, scale, block_q, block_k,
                group, bias_mode, bias_sq1, h_q, dropout_rate, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    has_bias = bias is not None
    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, has_bias=has_bias, bias_sq1=bias_sq1,
        dropout_rate=dropout_rate, sk_total=sk)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_k, d),
                     lambda b, i, j: (b // group, j, 0)),
    ]
    args = [kvlen, seed, q, k, v]
    if has_bias:
        g = _bias_group(bias_mode, h_q)
        in_specs.append(_bias_spec(bias_sq1, block_q, block_k, g,
                                   grid_ij=True))
        args.append(bias)
    # ptlint: disable=PT009 -- flash forward streams the FULL K/V per
    # query block by construction (online softmax): the seq/block_q
    # re-read is the O(block) -memory tradeoff the kernel exists for.
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels. dK/dV: grid (BH, nk, nq) accumulating over q blocks.
# dQ: grid (BH, nq, nk) accumulating over kv blocks. Probabilities are
# recomputed from the saved LSE; delta = rowsum(dO * O) is precomputed.
# ---------------------------------------------------------------------------


def _recompute_p(q_ref, k_ref, bias_ref, lse_ref, kvlen, i, j, causal,
                 scale, block_q, block_k, has_bias):
    q = q_ref[0]
    k = k_ref[0]
    lse = lse_ref[0][:, :1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if has_bias:
        s = s + bias_ref[0].astype(jnp.float32)
    s = _mask_cols(s, kvlen, i, j, block_q, block_k, causal)
    return jnp.exp(s - lse)


def _bwd_dkdv_kernel(*refs, causal, scale, block_q, block_k, has_bias,
                     bias_sq1, dropout_rate, sk_total):
    kvlen_ref, seed_ref, q_ref, k_ref, v_ref, do_ref = refs[:6]
    idx = 6
    bias_ref = refs[idx] if has_bias else None
    idx += int(has_bias)
    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc = refs[idx:idx + 6]

    bh = pl.program_id(0)
    j = pl.program_id(1)
    i = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    kvlen = kvlen_ref[bh]
    run = jnp.logical_and(
        ((i + 1) * block_q - 1 >= j * block_k) if causal else (i >= 0),
        j * block_k < kvlen)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        delta = delta_ref[0][:, :1]
        p = _recompute_p(q_ref, k_ref, bias_ref, lse_ref, kvlen, i, j,
                         causal, scale, block_q, block_k, has_bias)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], bh, i, j, block_q, block_k,
                              sk_total, dropout_rate)
            p_d = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        else:
            p_d = p
        dv_acc[...] += jax.lax.dot_general(
            p_d.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, causal, scale, block_q, block_k, has_bias,
                   bias_sq1, dropout_rate, sk_total):
    kvlen_ref, seed_ref, q_ref, k_ref, v_ref, do_ref = refs[:6]
    idx = 6
    bias_ref = refs[idx] if has_bias else None
    idx += int(has_bias)
    lse_ref, delta_ref, dq_ref, dq_acc = refs[idx:idx + 4]

    bh = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    kvlen = kvlen_ref[bh]
    run = jnp.logical_and(
        (j * block_k <= (i + 1) * block_q - 1) if causal else (j >= 0),
        j * block_k < kvlen)

    @pl.when(run)
    def _body():
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        delta = delta_ref[0][:, :1]
        p = _recompute_p(q_ref, k_ref, bias_ref, lse_ref, kvlen, i, j,
                         causal, scale, block_q, block_k, has_bias)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0], bh, i, j, block_q, block_k,
                              sk_total, dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_acc[...] += jax.lax.dot(ds, k,
                                   preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_backward(q, k, v, kvlen, seed, bias, out, lse, do, causal, scale,
                 block_q, block_k, group, bias_mode, bias_sq1, h_q,
                 dropout_rate, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nk = sq // block_q, sk // block_k
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True), (bh, sq, _LANES))
    has_bias = bias is not None

    kw = dict(causal=causal, scale=scale, block_q=block_q, block_k=block_k,
              has_bias=has_bias, bias_sq1=bias_sq1,
              dropout_rate=dropout_rate, sk_total=sk)
    g = _bias_group(bias_mode, h_q)

    # dK/dV pass: grid (b, j, i)
    kvspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    sdspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b // group, j, 0))
    okspec = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    rowspec = pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0))
    in_specs = [kvspec, sdspec, qspec, kspec, kspec, qspec]
    args = [kvlen, seed, q, k, v, do]
    if has_bias:
        in_specs.append(_bias_spec(bias_sq1, block_q, block_k, g,
                                   grid_ij=False))
        args.append(bias)
    in_specs += [rowspec, rowspec]
    args += [lse, delta]
    # dk/dv are produced per *query* head (b over B*Hq) and group-summed
    # below for GQA
    # ptlint: disable=PT009 -- dk/dv re-streams every Q/dO/LSE row
    # block per K/V tile (flash backward recomputation); inherent to
    # the tiling, not a blocking bug.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, **kw),
        grid=(bh, nk, nq),
        in_specs=in_specs,
        out_specs=[okspec, okspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    if group > 1:
        dk = dk.reshape(-1, group, sk, d).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(-1, group, sk, d).sum(axis=1).astype(v.dtype)

    # dQ pass: grid (b, i, j)
    kvspec2 = pl.BlockSpec(memory_space=pltpu.SMEM)
    sdspec2 = pl.BlockSpec(memory_space=pltpu.SMEM)
    qspec2 = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_k, d),
                          lambda b, i, j: (b // group, j, 0))
    rowspec2 = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    in_specs2 = [kvspec2, sdspec2, qspec2, kspec2, kspec2, qspec2]
    args2 = [kvlen, seed, q, k, v, do]
    if has_bias:
        in_specs2.append(_bias_spec(bias_sq1, block_q, block_k, g,
                                    grid_ij=True))
        args2.append(bias)
    in_specs2 += [rowspec2, rowspec2]
    args2 += [lse, delta]
    # ptlint: disable=PT009 -- dq re-streams the FULL K/V per query
    # block, mirroring the forward's online-softmax walk.
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw),
        grid=(bh, nq, nk),
        in_specs=in_specs2,
        out_specs=[qspec2],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args2)[0]
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wiring on the padded (BH, S, D) representation
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12,
                                                    13, 14, 15))
def _flash(q, k, v, kvlen, seed, bias, causal, scale, block_q, block_k,
           group, bias_mode, bias_sq1, h_q, dropout_rate, interpret):
    out, _ = _fa_forward(q, k, v, kvlen, seed, bias, causal, scale,
                         block_q, block_k, group, bias_mode, bias_sq1, h_q,
                         dropout_rate, interpret)
    return out


def _flash_fwd(q, k, v, kvlen, seed, bias, causal, scale, block_q, block_k,
               group, bias_mode, bias_sq1, h_q, dropout_rate, interpret):
    out, lse = _fa_forward(q, k, v, kvlen, seed, bias, causal, scale,
                           block_q, block_k, group, bias_mode, bias_sq1,
                           h_q, dropout_rate, interpret)
    return out, (q, k, v, kvlen, seed, bias, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, group, bias_mode, bias_sq1,
               h_q, dropout_rate, interpret, residuals, do):
    import numpy as np
    q, k, v, kvlen, seed, bias, out, lse = residuals
    dq, dk, dv = _fa_backward(q, k, v, kvlen, seed, bias, out, lse, do,
                              causal, scale, block_q, block_k, group,
                              bias_mode, bias_sq1, h_q, dropout_rate,
                              interpret)
    zero_int = lambda x: np.zeros(x.shape, jax.dtypes.float0)  # noqa: E731
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, zero_int(kvlen), zero_int(seed), dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def _tune_key(b, sq, sk, h_q, h_kv, d, dtype, causal, has_kvlens,
              has_bias, has_dropout):
    from paddle_tpu.ops.pallas.autotune import AutotuneCache
    return AutotuneCache.key(
        "flash_attention", b=b, sq=sq, sk=sk, hq=h_q, hkv=h_kv, d=d,
        dtype=str(dtype), causal=bool(causal), kvlens=bool(has_kvlens),
        bias=bool(has_bias), dropout=bool(has_dropout))


# measured default on a v5e chip (see flash_attention docstring); used
# when the autotune cache has no entry for the shape
_DEFAULT_BLOCKS = (256, 512)


def tune_flash_attention(q, k, v, causal=False, scale=None, kv_lens=None,
                         bias=None, dropout_p=0.0, dropout_seed=None,
                         candidates=None, include_bwd=True, iters=3):
    """Eagerly measure flash-attention block candidates on the REAL shapes
    and persist the winner (≙ auto_tune_base.h PickBestKernel — Pallas
    block sizes are trace-time constants, so tuning runs outside jit; any
    later ``flash_attention`` call on these shapes picks the tuned blocks
    from the cache at trace time). Returns ((block_q, block_k), timings).
    """
    import jax as _jax

    from paddle_tpu.ops.pallas import autotune as at

    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    b, sq, h_q, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    key = _tune_key(b, sq, sk, h_q, h_kv, d, q.dtype, causal,
                    kv_lens is not None, bias is not None, dropout_p > 0)
    if candidates is None:
        candidates = [(128, 128), (128, 256), (256, 256), (256, 512),
                      (512, 256), (512, 512), (1024, 512)]
    lim_q, lim_k = _round_up(sq, _LANES), _round_up(sk, _LANES)
    candidates = sorted({(min(bq, lim_q), min(bk, lim_k))
                         for bq, bk in candidates})

    # one jitted callable per candidate, built once: the timing loop must
    # measure kernel runtime, not re-trace/re-compile every call
    jitted = {}

    def build_and_run(cfg):
        if cfg not in jitted:
            bq, bk = cfg

            def fwd(q, k, v, _bq=bq, _bk=bk):
                o = flash_attention(q, k, v, causal=causal, scale=scale,
                                    kv_lens=kv_lens, bias=bias,
                                    dropout_p=dropout_p,
                                    dropout_seed=dropout_seed,
                                    block_q=_bq, block_k=_bk)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            fn = _jax.grad(fwd, argnums=(0, 1, 2)) if include_bwd else fwd
            jitted[cfg] = _jax.jit(fn)
        out = jitted[cfg](q, k, v)
        leaf = _jax.tree_util.tree_leaves(out)[0]
        float(leaf.reshape(-1)[0] if leaf.ndim else leaf)  # sync

    def geom_check(cfg):
        # static PT006 refusal (ISSUE 20): never compile/time a block
        # pair whose VMEM residency cannot fit
        from paddle_tpu.analysis import kernelmodel as km
        bq, bk = cfg

        def dry():
            _jax.eval_shape(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=causal, scale=scale,
                    kv_lens=kv_lens, bias=bias, dropout_p=dropout_p,
                    dropout_seed=dropout_seed, block_q=bq,
                    block_k=bk),
                q, k, v)
        return km.budget_reason(dry)

    return at.tune("flash_attention", key, candidates, build_and_run,
                   iters=iters, geom_check=geom_check)


def flash_attention(q, k, v, causal=False, scale=None, kv_lens=None,
                    bias=None, dropout_p=0.0, dropout_seed=None,
                    block_q=None, block_k=None, interpret=None):
    """Flash attention over (B, S, H, D) inputs; returns (B, S, Hq, D).

    Args:
      q: (B, Sq, Hq, D).
      k, v: (B, Sk, Hkv, D) with Hq % Hkv == 0 (GQA/MQA when Hkv < Hq).
      causal: lower-triangular mask; requires Sq == Sk.
      kv_lens: optional (B,) int32 — per example, keys at positions
        >= kv_lens[b] are masked out (contiguous key-padding mask, the
        BERT case). Blocks wholly beyond the valid length are skipped.
      bias: optional additive attention bias, shape broadcastable to
        (B, Hq, Sq, Sk) with leading dims each either full or 1. Constant
        w.r.t. differentiation (zero cotangent).
      dropout_p / dropout_seed: attention-probability dropout; the mask is
        a deterministic PRF of (seed, head, row, col). ``dropout_seed`` is
        a scalar int32 (array or python int).
      interpret: defaults to True off-TPU so tests run on CPU.

    Default blocks (256, 512) measured 1.48x over the XLA reference path at
    (8, 2048, 16, 64) bf16 fwd+bwd on a v5e chip; (128, 128) was 0.5x.
    """
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    b, sq, h_q, d = q.shape
    sk, h_kv = k.shape[1], k.shape[2]
    if h_q % h_kv:
        raise ValueError(f"GQA needs Hq % Hkv == 0, got {h_q} vs {h_kv}")
    group = h_q // h_kv
    if causal and sq != sk:
        raise ValueError(
            f"causal flash attention needs sq == sk, got {sq} vs {sk}")
    if dropout_p >= 1.0 or dropout_p < 0.0:
        raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    if block_q is None or block_k is None:
        # trace-time cache lookup (tune_flash_attention fills it); the
        # measured v5e default otherwise
        from paddle_tpu.ops.pallas.autotune import get_cache
        hit = get_cache().get(_tune_key(
            b, sq, sk, h_q, h_kv, d, q.dtype, causal, kv_lens is not None,
            bias is not None, dropout_p > 0))
        tuned = hit if hit is not None else _DEFAULT_BLOCKS
        block_q = block_q if block_q is not None else tuned[0]
        block_k = block_k if block_k is not None else tuned[1]

    # clamp blocks for short sequences — padding 128 rows up to a 256/512
    # block would multiply the real work
    block_q = min(block_q, _round_up(sq, _LANES))
    block_k = min(block_k, _round_up(sk, _LANES))
    sq_p = _round_up(max(sq, block_q), block_q)
    sk_p = _round_up(max(sk, block_k), block_k)
    # D is NOT padded: Mosaic accepts a block dim equal to the full array
    # dim, and zero-padding 64→128 would double the contraction FLOPs.

    def to3(x, s_p):
        hh = x.shape[2]
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * hh, x.shape[1], d)
        return jnp.pad(x, ((0, 0), (0, s_p - x.shape[1]), (0, 0)))

    if kv_lens is None:
        kvlen3 = jnp.full((b * h_q,), sk, jnp.int32)
    else:
        kv_lens = jnp.minimum(jnp.asarray(kv_lens, jnp.int32), sk)
        kvlen3 = jnp.repeat(kv_lens, h_q)

    seed_arr = jnp.reshape(
        jnp.asarray(0 if dropout_seed is None else dropout_seed,
                    jnp.int32), (1,))

    bias_mode = "one"
    bias_sq1 = False
    bias3 = None
    if bias is not None:
        # -inf is a legal mask value for callers; keep it finite in-kernel
        bias = jnp.maximum(jnp.asarray(bias, jnp.float32), -1e30)
        # broadcast b/h/sk, but keep a size-1 Sq dim: the kernel's bias
        # block pins it to one row, so a key-only mask never materializes
        # the (.., Sq, Sk) tensor in HBM
        bias = jnp.broadcast_to(
            bias, jnp.broadcast_shapes(bias.shape, (1, 1, 1, sk)))
        if bias.ndim != 4:
            raise ValueError(f"bias must be 4-D, got {bias.shape}")
        bb, bh_, bsq, _ = bias.shape
        if bsq not in (1, sq):
            raise ValueError(f"bias Sq dim must be 1 or {sq}, got {bsq}")
        bias_sq1 = bsq == 1
        if (bb, bh_) == (1, 1):
            bias_mode = "one"
        elif bh_ == 1:
            bias_mode = "batch"
        elif bb == 1:
            bias_mode = "head"
        else:
            bias_mode = "bh"
        bias3 = bias.reshape(bb * bh_, bsq, sk)
        bias3 = jnp.pad(bias3, ((0, 0), (0, 0 if bias_sq1 else sq_p - sq),
                                (0, sk_p - sk)))

    out3 = _flash(to3(q, sq_p), to3(k, sk_p), to3(v, sk_p), kvlen3,
                  seed_arr, bias3, causal, float(scale), block_q, block_k,
                  group, bias_mode, bias_sq1, h_q, float(dropout_p),
                  bool(interpret))
    out = out3[:, :sq, :].reshape(b, h_q, sq, d)
    return jnp.transpose(out, (0, 2, 1, 3))


def ptgeom_cases():
    """Geometry registry for tools/ptgeom.py (ISSUE 20): the bench
    ladder x the autotune block-candidate space, forward and backward,
    driven under jax.eval_shape (nothing executes)."""
    from paddle_tpu.analysis import kernelmodel as km

    def case(geom, bq, bk, bwd=False):
        p = km.LADDER[geom]
        d = p["dm"] // p["heads"]
        q = km.sds((1, p["seq"], p["heads"], d), p["dtype"])

        def run():
            import jax as _jax

            def fwd(q, k, v):
                o = flash_attention(q, k, v, causal=True, block_q=bq,
                                    block_k=bk)
                return jnp.sum(o.astype(jnp.float32))

            fn = _jax.grad(fwd, argnums=(0, 1, 2)) if bwd else (
                lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                block_q=bq,
                                                block_k=bk))
            _jax.eval_shape(fn, q, q, q)
        return km.GeomCase(
            kernel="flash_attention", geometry=geom,
            config=f"bq{bq}.bk{bk}" + (".bwd" if bwd else ""), run=run)

    cases = [case("tiny", 256, 512)]
    for geom in ("350m", "r06"):
        for bq, bk in ((128, 128), (256, 512), (512, 512),
                       (1024, 512)):
            cases.append(case(geom, bq, bk))
        cases.append(case(geom, 256, 512, bwd=True))
    return cases
