"""Weight-only int8 matmul as a Pallas TPU kernel.

Reference analog: the int8 fused GEMM inventory —
paddle/fluid/operators/fused/attn_gemm_int8.h, quant_dequant_kernel.h,
and cublasLt int8 matmul dispatch. On TPU the win is different: decode is
HBM-bandwidth bound, so the kernel's job is to stream the weight matrix
through VMEM as int8 (4x less HBM traffic than fp32, 2x less than bf16)
and dequantize per-tile right before the MXU contraction. XLA's own
convert-fusion materializes the dequantized tile too, but only this
kernel guarantees the int8→float convert never round-trips HBM and lets
us pick MXU-shaped tiles.

Inference-only: gradients flow to the activation x (straight-through
w.r.t. the dequantized weight is the XLA path's job; serving never needs
dw).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["int8_matmul"]

_LANES = 128


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _kernel(x_ref, q_ref, scale_ref, o_ref, acc_ref):
    k = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    w = q_ref[...].astype(x.dtype)
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _done():
        o_ref[...] = (acc_ref[...]
                      * scale_ref[...].astype(jnp.float32)
                      ).astype(o_ref.dtype)


def int8_matmul(x, q, scale, block_m: int = 256, block_n: int = 512,
                block_k: int = 512, interpret=None):
    """``(x @ q.astype(float)) * scale`` with q int8, scale per-column.

    x: (..., K) float; q: (K, N) int8; scale: (N,) or (1, N) fp32.
    Returns (..., N) in x.dtype. Off-TPU runs in interpreter mode.
    """
    x = jnp.asarray(x)
    q = jnp.asarray(q)
    scale = jnp.asarray(scale, jnp.float32).reshape(1, -1)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    lead = x.shape[:-1]
    kdim = x.shape[-1]
    n = q.shape[1]
    assert q.shape[0] == kdim, (x.shape, q.shape)
    x2 = x.reshape(-1, kdim)
    m = x2.shape[0]

    # decode has tiny M — clamp blocks so padding never multiplies work
    block_m = min(block_m, _round_up(m, 8))
    block_n = min(block_n, _round_up(n, _LANES))
    block_k = min(block_k, _round_up(kdim, _LANES))
    m_p, n_p, k_p = (_round_up(m, block_m), _round_up(n, block_n),
                     _round_up(kdim, block_k))
    if (m_p, k_p) != (m, kdim):
        x2 = jnp.pad(x2, ((0, m_p - m), (0, k_p - kdim)))
    if (k_p, n_p) != (kdim, n):
        q = jnp.pad(q, ((0, k_p - kdim), (0, n_p - n)))
    if n_p != n:
        scale = jnp.pad(scale, ((0, 0), (0, n_p - n)))

    # ptlint: disable=PT009 -- K-blocked matmul: x re-reads once per N
    # tile and q once per M tile — the classic blocked-GEMM streaming
    # pattern; re-read factor is bounded by the block_n/block_m sweep
    # the autotuner already prices in wall time.
    out = pl.pallas_call(
        _kernel,
        grid=(m_p // block_m, n_p // block_n, k_p // block_k),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_k, block_n), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, block_n), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_p, n_p), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x2, q, scale)
    return out[:m, :n].reshape(*lead, n)


def ptgeom_cases():
    """Geometry registry for tools/ptgeom.py (ISSUE 20): the MLP-width
    int8 matmul at train-like and decode-like M, under
    jax.eval_shape."""
    from paddle_tpu.analysis import kernelmodel as km

    def case(geom, m, bm, bn, bk):
        p = km.LADDER[geom]
        kdim = p["dm"]
        n = 4 * p["dm"]
        x = km.sds((m, kdim), p["dtype"])
        qm = km.sds((kdim, n), "int8")
        sc = km.sds((n,), "float32")

        def run():
            import jax as _jax
            _jax.eval_shape(
                lambda x, qm, sc: int8_matmul(
                    x, qm, sc, block_m=bm, block_n=bn, block_k=bk),
                x, qm, sc)
        return km.GeomCase(kernel="int8_matmul", geometry=geom,
                           config=f"m{m}.bm{bm}.bn{bn}.bk{bk}",
                           run=run)

    cases = []
    for geom in ("350m", "r06"):
        cases.append(case(geom, 2048, 256, 512, 512))
        cases.append(case(geom, 8, 256, 512, 512))
    return cases
