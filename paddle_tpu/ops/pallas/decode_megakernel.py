"""Layer-folded single-launch paged decode: the whole transformer stack
(per-layer norm -> QKV -> fused paged append+attend -> MLP residual)
runs as ONE Pallas kernel with the grid's outer dimension over layers,
followed by ONE fused final-norm -> logits -> greedy-argmax epilogue
kernel — two launches per decode step instead of O(layers) (ISSUE 19;
PAPERS "LLM Inference Acceleration via Efficient Operation Fusion").

Why: every r05 hardware number says short-length decode is LAUNCH-bound,
not HBM-bound (paged 387 tok/s = 0.17x roofline, prof/launch_tax_frac
from PR 15). The per-layer fused path (`paged_append_attend` inside a
`lax.scan`) still pays one kernel dispatch per layer per step; folding
the layer loop INTO the grid amortizes the dispatch to one program
launch riding PR 8's stacked-block weights ((L, ...) leaves — the grid
index IS the layer index, weight slabs stream per grid step via their
BlockSpec index maps) and the PR 6 layer-folded pools (page p of layer
l at row l*P + p; ONE scratch row at L*P catches inactive slots'
writes).

Kernel shape:

- ``mega_decode_layers`` — grid (L,), ``dimension_semantics
  ("arbitrary",)`` (layer l+1 reads layer l's hidden state from the
  revisited output block, which stays resident in VMEM across
  sequential grid steps). The KV pools ride in ``ANY`` memory space
  (they are far too big to block into VMEM whole) and are
  input/output-aliased, so fresh-row writes are in place and the
  attention loop reads the just-written rows of earlier draft
  positions directly. Numerics reuse the ONE shared online-softmax
  definition (`decode_attention.online_softmax_step`); pages past a
  row's bound are fully masked, which the running-max clamp turns into
  an exact no-op — so no per-page predication is needed for parity.
- ``mega_logits_sample`` — grid over vocab tiles of the logits matmul,
  streaming the (dm, vb) weight slabs; a running blockwise argmax
  (strict-greater update + min-index tie-break = jnp.argmax's
  first-max semantics) and a non-finite flag accumulate in VMEM
  scratch, and the LAST tile writes one packed (B, 128) int32 output:
  column 0 = greedy token, column 1 = non-finite flag. The (S, V)
  logits never materialize in HBM.

Rows are FLAT (B = slots, or slots*K for speculative verify): each row
carries its own (slot, position, write?) coordinates via scalar
prefetch, so the plain step and the speculative K-row verify are the
SAME program at different row counts — verify/accept rides the same
single-dispatch geometry (satellite: revive spec decode on paged).

Bit-parity contract: greedy token STREAMS are bit-identical to the
per-layer fused path (`PagedDecodeEngine` with ``mega=False``) — the
per-layer path stays as the interpret-mode-asserted reference. Logits
may differ in the last ulp (different accumulation order folding the
fresh row), which greedy argmax absorbs; the engine's parity tests
assert the stream, the same contract the paged engine already holds
against ``gpt.generate``.

Forward-only (decode never differentiates through the pools).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas.decode_attention import (
    _LANES, _NEG_INF, online_softmax_init, online_softmax_step)

__all__ = ["mega_decode_layers", "mega_logits_sample",
           "tune_mega_epilogue"]

# fallback vocab-tile width when the autotune cache has no entry for
# the folded shape family
_DEFAULT_VB = 512

# stacked-weight streaming order (the kernel ABI); optional biases are
# simply absent from the operand list when the model has none
_WEIGHT_ORDER = ("ln1_scale", "ln1_bias", "wqkv", "bqkv", "wo", "bo",
                 "ln2_scale", "ln2_bias", "wup", "bup", "wdown",
                 "bdown")


def _mega_tune_key(dm, vocab, dtype, layers, page):
    """Autotune key over the FOLDED geometry: the epilogue tile width
    depends on the logits matmul family (dm, vocab, dtype), and
    distinct layer-fold/page geometries tune separately (their VMEM
    budget differs)."""
    from paddle_tpu.ops.pallas.autotune import AutotuneCache
    return AutotuneCache.key("paged_mega", dm=dm, vocab=vocab,
                             dtype=str(dtype), layers=layers, page=page)


def _resolve_vb(vb, dm, vocab, dtype, layers, page):
    if vb is None:
        from paddle_tpu.ops.pallas.autotune import get_cache
        hit = get_cache().get(_mega_tune_key(dm, vocab, dtype, layers,
                                             page))
        if isinstance(hit, (tuple, list)):
            hit = hit[0]
        vb = hit if hit is not None else _DEFAULT_VB
    # ptlint: disable=PT001 -- vb is a static Python config knob
    # (autotune-cache hit or explicit kwarg), never a device value
    vb = max(_LANES, int(vb) // _LANES * _LANES)
    # PT006 clamp (ISSUE 20): the epilogue streams a double-buffered
    # (dm, vb) weight slab through VMEM — cap vb so that slab can never
    # exceed half the static budget (the other half covers the hidden
    # state, accumulators, and the packed output), no matter what the
    # autotune cache or an explicit kwarg asked for at large vocab.
    from paddle_tpu.analysis.kernelmodel import (itemsize,
                                                 vmem_budget_bytes)
    cap = vmem_budget_bytes() // 2 // (2 * dm * itemsize(dtype))
    cap = max(_LANES, cap // _LANES * _LANES)
    return min(vb, cap)


def _const_map(n):
    def index(l, *prefetch):
        return (0,) * n
    return index


def _layer_map(n):
    def index(l, *prefetch):
        return (l,) + (0,) * (n - 1)
    return index


def _mega_kernel(*refs, wnames, L, B, dm, hq, hkv, d, page, P, mx,
                 group, gp, scale, rope, theta, moved=None):
    # ABI: | pos, slot, write, table (SMEM scalar prefetch)
    #      | x, pos_v, <stacked weight slabs>, kp, vp  (inputs)
    #      | x_out, kp_out, vp_out                     (outputs)
    #      | o_scratch, acc, m, l                      (VMEM scratch)
    # kp/vp (inputs) are consumed by the aliasing, not the body — the
    # pool state is read and written through the ALIASED output refs,
    # so earlier rows' fresh writes are visible to later reads.
    pos_s, slot_s, write_s, tab_s = refs[:4]
    i = 4
    x_ref, posv_ref = refs[i], refs[i + 1]
    i += 2
    w = {}
    for name in wnames:
        w[name] = refs[i]
        i += 1
    i += 2                                   # kp_in, vp_in (aliased)
    xo_ref, kpo_ref, vpo_ref = refs[i:i + 3]
    os_ref, acc_ref, m_ref, l_ref = refs[i + 3:i + 7]
    pool_dt = kpo_ref.dtype
    li = pl.program_id(0)

    @pl.when(li == 0)
    def _seed():
        xo_ref[...] = x_ref[...]

    x = xo_ref[...]                                        # (B, dm)

    # --- LN1 + fused QKV (+ rope), mirrors GPTBlock._qkv ------------
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    h = ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * w["ln1_scale"][0]
         + w["ln1_bias"][0]).astype(x.dtype)
    qkv = h @ w["wqkv"][0]
    if "bqkv" in w:
        qkv = qkv + w["bqkv"][0]
    q = qkv[:, :hq * d].reshape(B, hq, d)
    k = qkv[:, hq * d:(hq + hkv) * d].reshape(B, hkv, d)
    v = qkv[:, (hq + hkv) * d:].reshape(B, hkv, d)
    if rope:
        half = d // 2
        posf = posv_ref[...].astype(jnp.float32)           # (B, 1)
        freqs = theta ** (-jax.lax.broadcasted_iota(
            jnp.float32, (1, half), 1) / half)
        ang = posf * freqs                                 # (B, half)
        cos = jnp.cos(ang)[:, None, :]
        sin = jnp.sin(ang)[:, None, :]

        def rot(t):
            t32 = t.astype(jnp.float32)
            t1, t2 = t32[..., :half], t32[..., half:]
            return jnp.concatenate(
                [t1 * cos - t2 * sin, t1 * sin + t2 * cos],
                axis=-1).astype(t.dtype)

        q, k = rot(q), rot(k)
    krow = k.astype(pool_dt)                               # (B, hkv, d)
    vrow = v.astype(pool_dt)
    qg = q.astype(pool_dt).reshape(B * hkv, group, d)
    if gp > group:
        qg = jnp.concatenate(
            [qg, jnp.zeros((B * hkv, gp - group, d), pool_dt)], axis=1)

    # --- fresh-row writes, ALL rows before any attend ---------------
    # Row r's KV lands at page table[slot, pos//page] offset pos%page
    # of THIS layer's pool slab; masked-out rows write the shared
    # scratch row L*P instead (same convention as the per-layer fused
    # path's wpids). Writing every row first is causal because the
    # attend bound pos+1 masks any column at a LATER draft position.
    def write_row(r, _):
        s = slot_s[r]
        p = pos_s[r]
        pid = tab_s[s * mx + jnp.minimum(p // page, mx - 1)]
        g = jnp.where(write_s[r] == 1, li * P + pid, L * P)
        off = p % page
        for hh in range(hkv):
            kpo_ref[g, hh, pl.ds(off, 1), :] = jax.lax.dynamic_slice(
                krow, (r, hh, 0), (1, 1, d)).reshape(1, d)
            vpo_ref[g, hh, pl.ds(off, 1), :] = jax.lax.dynamic_slice(
                vrow, (r, hh, 0), (1, 1, d)).reshape(1, d)
        return 0

    jax.lax.fori_loop(0, B, write_row, 0)

    # --- paged attention per (row, kv head) -------------------------
    # Pages past the bound are fully masked; online_softmax_step's
    # running-max clamp makes a fully-masked block an exact no-op
    # (alpha == 1, p == 0), so unconditional stepping over the fixed
    # mx-wide table is bit-identical to the per-layer kernel's
    # pl.when-guarded stream.
    def attend(rh, _):
        r = rh // hkv
        hh = rh % hkv
        s = slot_s[r]
        bound = pos_s[r] + 1
        online_softmax_init(acc_ref, m_ref, l_ref)
        qt = jax.lax.dynamic_slice(qg, (rh, 0, 0),
                                   (1, gp, d)).reshape(gp, d)

        def one_page(j, _):
            g = li * P + tab_s[s * mx + j]
            online_softmax_step(qt, kpo_ref[g, hh], vpo_ref[g, hh],
                                j * page, bound, acc_ref, m_ref, l_ref,
                                scale)
            return 0

        jax.lax.fori_loop(0, mx, one_page, 0)
        lv = l_ref[:, :1]
        os_ref[rh] = (acc_ref[...]
                      / jnp.where(lv == 0.0, 1.0, lv)).astype(pool_dt)
        return 0

    jax.lax.fori_loop(0, B * hkv, attend, 0)

    # --- out-proj + MLP residual, mirrors GPTBlock._block_tail ------
    attn = os_ref[...][:, :group, :].reshape(B, hq * d).astype(x.dtype)
    o = attn @ w["wo"][0]
    if "bo" in w:
        o = o + w["bo"][0]
    x = x + o
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    h = ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * w["ln2_scale"][0]
         + w["ln2_bias"][0]).astype(x.dtype)
    h = jax.nn.gelu(h @ w["wup"][0]
                    + (w["bup"][0] if "bup" in w else 0.0))
    h = h @ w["wdown"][0]
    if "bdown" in w:
        h = h + w["bdown"][0]
    xo_ref[...] = x + h


def mega_decode_layers(x, weights, k_pages, v_pages, page_table,
                       positions, row_slot, row_write, *, page, n_pages,
                       n_heads, kv_heads, head_dim, rope=False,
                       rope_theta=10000.0, scale=None, interpret=None):
    """Run the WHOLE layer stack of one decode step in one launch.

    Args:
      x: (B, dm) embedded input rows (token + positional embedding
        already applied). B is flat: one row per slot for the plain
        step, slots*K rows (slot-major) for speculative verify.
      weights: dict of scan-stacked block leaves — ``ln1_scale``,
        ``ln1_bias``, ``wqkv``, ``wo``, ``ln2_scale``, ``ln2_bias``,
        ``wup``, ``wdown`` each (L, ...), plus the optional biases
        (``bqkv``/``bo``/``bup``/``bdown``) or None.
      k_pages, v_pages: (L*n_pages+1, Hkv, page, D) layer-folded pools
        (DONATED — aliased into the returned pools). Row L*n_pages is
        the scratch page for masked-out rows.
      page_table: (S, max_pages) int32, UNFOLDED local page ids (the
        kernel folds in the layer offset l*n_pages itself).
      positions: (B,) int32 — row r's absolute position; its fresh KV
        row lands there and it attends over [0, positions[r]].
      row_slot: (B,) int32 — row r's slot (page-table row).
      row_write: (B,) int32 — 1: write the fresh row into the slot's
        page, 0: divert to the scratch page (inactive slot).

    Returns (x_out, k_pages, v_pages): x_out (B, dm) is the final
    hidden state after all L blocks (pre final-norm — feed it to
    `mega_logits_sample`).
    """
    x = jnp.asarray(x)
    k_pages, v_pages = jnp.asarray(k_pages), jnp.asarray(v_pages)
    B, dm = x.shape
    # ptlint: disable=PT001 -- geometry kwargs are static Python ints
    hq, hkv, d = int(n_heads), int(kv_heads), int(head_dim)
    page = int(page)  # ptlint: disable=PT001 -- static config knob
    P = int(n_pages)  # ptlint: disable=PT001 -- static config knob
    L = weights["wqkv"].shape[0]
    S, mx = page_table.shape
    if k_pages.shape[0] != L * P + 1:
        raise ValueError(
            f"layer-folded pool expects {L}*{P}+1 rows, got "
            f"{k_pages.shape[0]}")
    if page % _LANES:
        raise ValueError(f"page_size {page} must be a multiple of "
                         f"{_LANES}")
    if hq % hkv:
        raise ValueError(f"GQA needs Hq % Hkv == 0, got {hq} vs {hkv}")
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    pool_dt = k_pages.dtype
    sub = 16 if pool_dt in (jnp.bfloat16, jnp.float16) else 8
    gp = max(sub, (group + sub - 1) // sub * sub)

    prefetch = (jnp.asarray(positions, jnp.int32),
                jnp.asarray(row_slot, jnp.int32),
                jnp.asarray(row_write, jnp.int32),
                jnp.asarray(page_table, jnp.int32).reshape(-1))
    posv = jnp.asarray(positions, jnp.int32).reshape(B, 1)

    wnames = tuple(n for n in _WEIGHT_ORDER
                   if weights.get(n) is not None)
    any_spec = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
    in_specs = [pl.BlockSpec((B, dm), _const_map(2)),
                pl.BlockSpec((B, 1), _const_map(2))]
    operands = [x, posv]
    for n in wnames:
        wa = jnp.asarray(weights[n])
        in_specs.append(pl.BlockSpec((1,) + wa.shape[1:],
                                     _layer_map(wa.ndim)))
        operands.append(wa)
    in_specs += [any_spec, any_spec]
    operands += [k_pages, v_pages]
    out_specs = [pl.BlockSpec((B, dm), _const_map(2)), any_spec,
                 any_spec]
    out_shape = [jax.ShapeDtypeStruct((B, dm), x.dtype),
                 jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                 jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype)]
    # operand numbering counts the scalar-prefetch refs: 4 prefetch +
    # x + pos_v + the weight slabs, then the two pools
    nw = len(wnames)
    aliases = {4 + 2 + nw: 1, 4 + 2 + nw + 1: 2}

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(L,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((B * hkv, gp, d), pool_dt),
            pltpu.VMEM((gp, d), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
            pltpu.VMEM((gp, _LANES), jnp.float32),
        ],
    )
    # ptlint: disable=PT006 -- the layer fold streams each layer's FULL
    # weight slab per grid step (~96 MiB/layer at r06 scale, ~12x the
    # 16 MiB core budget double-buffered; see docs/serving.md for the
    # measured fractions): over budget BY CONSTRUCTION until the stack
    # is dm-tiled. Kept deliberate — the r06 recapture (ROADMAP item 1)
    # measures whether Mosaic's windowing absorbs it; ptgeom's table
    # keeps the number visible per geometry either way.
    return pl.pallas_call(
        functools.partial(_mega_kernel, wnames=wnames, L=L, B=B, dm=dm,
                          hq=hq, hkv=hkv, d=d, page=page, P=P, mx=mx,
                          group=group, gp=gp,
                          # ptlint: disable=PT001 -- static float kwarg
                          scale=float(scale),
                          # ptlint: disable=PT001 -- static knobs
                          rope=bool(rope), theta=float(rope_theta)),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*prefetch, *operands)


def _epilogue_kernel(x_ref, s_ref, b_ref, w_ref, p_ref, out_ref,
                     hs_ref, best_ref, arg_ref, nf_ref, *, B, vb,
                     vocab):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        xs = x_ref[...]
        x32 = xs.astype(jnp.float32)
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        y = ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * s_ref[0]
             + b_ref[0])
        hs_ref[...] = y.astype(xs.dtype)
        best_ref[...] = jnp.full_like(best_ref, _NEG_INF)
        arg_ref[...] = jnp.zeros_like(arg_ref)
        nf_ref[...] = jnp.zeros_like(nf_ref)

    lg = hs_ref[...] @ w_ref[...]                     # (B, vb)
    lg = jnp.where(p_ref[...] > 0, jnp.nan, lg)
    lgf = lg.astype(jnp.float32)
    col = (j * vb
           + jax.lax.broadcasted_iota(jnp.int32, (B, vb), 1))
    valid = col < vocab
    nfb = jnp.any(valid & ~jnp.isfinite(lgf), axis=1, keepdims=True)
    lgm = jnp.where(valid, lgf, _NEG_INF)
    bm = jnp.max(lgm, axis=1, keepdims=True)          # (B, 1)
    first = jnp.min(jnp.where((lgm == bm) & valid, col,
                              jnp.int32(2 ** 30)),
                    axis=1, keepdims=True)
    # strict > keeps the FIRST max across tiles (jnp.argmax semantics);
    # a NaN bm compares False, so poisoned rows keep arg 0 — they are
    # flagged non-finite and the engine discards their token anyway
    upd = bm > best_ref[:, :1]
    best_ref[...] = jnp.where(upd, bm, best_ref[...])
    arg_ref[...] = jnp.where(upd, first, arg_ref[...])
    nf_ref[...] = nf_ref[...] | nfb.astype(jnp.int32)

    @pl.when(j == pl.num_programs(0) - 1)
    def _emit():
        out_ref[...] = jnp.concatenate(
            [arg_ref[:, :1], nf_ref[:, :1],
             jnp.zeros((B, _LANES - 2), jnp.int32)], axis=1)


def mega_logits_sample(x, lnf_scale, lnf_bias, w, poison, *, vb=None,
                       layers=0, page=0, interpret=None):
    """Fused final-norm -> logits -> greedy sampling epilogue.

    Streams the (dm, V) unembedding in (dm, vb) tiles with a running
    blockwise argmax, so the logits never land in HBM and sampling
    costs ONE launch. x: (B, dm) post-stack hidden rows; w: (dm, V)
    unembedding (pass ``head["wte"].T`` or ``head["lm_head"]``);
    poison: (B,) bool/int — rows to force non-finite (the engine's
    fault-injection contract: poisoned rows flag, never emit).

    Returns (tok, nonfin): (B,) int32 greedy tokens (first-max index,
    jnp.argmax parity) and (B,) int32 non-finite flags (1 where any
    true-vocab logit is NaN/inf — the engine's ``bad`` source).

    ``vb`` (vocab tile width) defaults from the autotune cache keyed by
    the folded geometry (`tune_mega_epilogue` fills it), else 512.
    """
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    B, dm = x.shape
    vocab = w.shape[1]
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    vb = _resolve_vb(vb, dm, vocab, x.dtype, layers, page)
    vb = min(vb, (vocab + _LANES - 1) // _LANES * _LANES)
    nj = (vocab + vb - 1) // vb
    wp = jnp.pad(w, ((0, 0), (0, nj * vb - vocab)))
    pois = jnp.asarray(poison).astype(jnp.int32).reshape(B, 1)

    out = pl.pallas_call(
        functools.partial(_epilogue_kernel, B=B, vb=vb, vocab=vocab),
        grid=(nj,),
        in_specs=[
            pl.BlockSpec((B, dm), _const_map(2)),
            pl.BlockSpec((1, dm), _const_map(2)),
            pl.BlockSpec((1, dm), _const_map(2)),
            pl.BlockSpec((dm, vb), lambda j: (0, j)),
            pl.BlockSpec((B, 1), _const_map(2)),
        ],
        out_specs=pl.BlockSpec((B, _LANES), _const_map(2)),
        out_shape=jax.ShapeDtypeStruct((B, _LANES), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((B, dm), x.dtype),
            pltpu.VMEM((B, _LANES), jnp.float32),
            pltpu.VMEM((B, _LANES), jnp.int32),
            pltpu.VMEM((B, _LANES), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, jnp.asarray(lnf_scale).reshape(1, dm),
      jnp.asarray(lnf_bias).reshape(1, dm), wp, pois)
    return out[:, 0], out[:, 1]


def tune_mega_epilogue(x, lnf_scale, lnf_bias, w, *, layers=0, page=0,
                       candidates=None, iters=3):
    """Measure epilogue vocab-tile candidates on the REAL head shapes
    and persist the winner keyed by the folded geometry (see
    `autotune.tune`; run before the engine traces — Pallas grids are
    trace-time constants)."""
    from paddle_tpu.ops.pallas import autotune as at

    x = jnp.asarray(x)
    w = jnp.asarray(w)
    vocab = w.shape[1]
    key = _mega_tune_key(x.shape[1], vocab, x.dtype, layers, page)
    if candidates is None:
        candidates = [c for c in (256, 512, 1024, 2048)
                      if c <= (vocab + _LANES - 1) // _LANES * _LANES
                      ] or [_LANES]
    poison = jnp.zeros((x.shape[0],), bool)
    jitted = {}

    def build_and_run(vb):
        if vb not in jitted:
            def fn(x, w, _vb=int(vb)):
                tok, nf = mega_logits_sample(
                    x, lnf_scale, lnf_bias, w, poison, vb=_vb,
                    layers=layers, page=page)
                return tok.sum() + nf.sum()
            jitted[vb] = jax.jit(fn)
        int(jitted[vb](x, w))  # sync — timing must see the kernel end

    def geom_check(vb):
        # refuse before spending chip time: a candidate the PT006
        # budget clamp would coerce is a duplicate of the clamped
        # width, and an over-budget harvest can never fit
        from paddle_tpu.analysis import kernelmodel as km
        rvb = _resolve_vb(int(vb), x.shape[1], vocab, x.dtype, layers,
                          page)
        if rvb != int(vb):
            return (f"vb={int(vb)} infeasible: PT006 VMEM budget "
                    f"clamps the epilogue tile to {rvb}")

        def dry():
            jax.eval_shape(
                lambda x, s, b, w, p: mega_logits_sample(
                    x, s, b, w, p, vb=int(vb), layers=layers,
                    page=page),
                x, jnp.asarray(lnf_scale), jnp.asarray(lnf_bias), w,
                poison)
        return km.budget_reason(dry)

    return at.tune("paged_mega", key, candidates, build_and_run,
                   iters=iters, geom_check=geom_check)


def ptgeom_cases():
    """Geometry registry for tools/ptgeom.py (ISSUE 20): drive both
    megakernel launches under ``jax.eval_shape`` across the bench
    ladder and the epilogue's autotune vb candidates, so PT006-PT009
    can price every launch without executing a kernel."""
    from paddle_tpu.analysis import kernelmodel as km

    def stack_case(geom, L=None):
        p = km.LADDER[geom]
        dm, hq, hkv = p["dm"], p["heads"], p["kv_heads"]
        d = dm // hq
        dt = p["dtype"]
        layers = p["layers"] if L is None else L
        page = p["page"]
        P = max(1, p["seq"] // page)
        B = 8
        weights = {
            "ln1_scale": km.sds((layers, dm), dt),
            "ln1_bias": km.sds((layers, dm), dt),
            "wqkv": km.sds((layers, dm, (hq + 2 * hkv) * d), dt),
            "wo": km.sds((layers, hq * d, dm), dt),
            "ln2_scale": km.sds((layers, dm), dt),
            "ln2_bias": km.sds((layers, dm), dt),
            "wup": km.sds((layers, dm, 4 * dm), dt),
            "wdown": km.sds((layers, 4 * dm, dm), dt),
        }
        x = km.sds((B, dm), dt)
        pool = km.sds((layers * P + 1, hkv, page, d), dt)
        table = km.sds((B, P), "int32")
        rows = km.sds((B,), "int32")

        def run():
            jax.eval_shape(
                functools.partial(mega_decode_layers, page=page,
                                  n_pages=P, n_heads=hq,
                                  kv_heads=hkv, head_dim=d),
                x, weights, pool, pool, table, rows, rows, rows)
        return km.GeomCase(kernel="mega_decode_layers", geometry=geom,
                           config=f"L{layers}.page{page}", run=run)

    def epi_case(geom, vb):
        p = km.LADDER[geom]
        dm, vocab, dt = p["dm"], p["vocab"], p["dtype"]
        B = 8
        x = km.sds((B, dm), dt)
        vec = km.sds((dm,), dt)
        w = km.sds((dm, vocab), dt)
        pois = km.sds((B,), "int32")

        def run():
            jax.eval_shape(
                functools.partial(mega_logits_sample, vb=vb),
                x, vec, vec, w, pois)
        return km.GeomCase(kernel="mega_logits_sample", geometry=geom,
                           config=f"vb{vb}", run=run)

    cases = [stack_case(g) for g in ("tiny", "350m", "r06")]
    for g in ("tiny", "350m", "r06"):
        for vb in (256, 512, 2048):
            cases.append(epi_case(g, vb))
    return cases
