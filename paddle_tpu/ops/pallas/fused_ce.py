"""Fused blockwise softmax-cross-entropy over a projection — the (N, V)
logits never exist in HBM, forward or backward.

Reference analog: paddle/fluid/operators/collective/
c_softmax_with_cross_entropy_op.cu:38-192, which fuses the softmax-CE of
TP-sharded logits so no rank materializes the full vocab row. On TPU the
bigger prize is the *dense* case: at (B=8, S=2048, V=50304) the fp32
logits + grads are ~6.6 GB of HBM traffic per step that this kernel never
pays. Three Pallas passes, each streaming (block_n, block_v) logit tiles
recomputed in VMEM:

  fwd : online logsumexp over vocab blocks + gather of the label logit
        → per-row loss and lse (the only (N,)-sized residual).
  dx  : p = exp(x·wᵀ − lse); dx += (p − onehot)·g @ w_block.
  dw  : same recompute, accumulated over row blocks into (block_v, d).

Weights ride in embedding layout (V, d) — the tied LM head (wte) feeds the
kernel directly, no transposed copy.

Cost model: 5 logit-matmul passes of N·V·d MACs total (1 fwd + 2 recompute
+ dx + dw) vs the unfused 3 — a deliberate FLOPs-for-bandwidth trade; the
unfused path is HBM-bound on the logit round-trips, and the MXU has the
headroom (GPT-1.3B single-chip sits at ~0.50 MFU).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_softmax_cross_entropy"]

_LANES = 128
_NEG_INF = float("-inf")


def _logits_block(x_ref, w_ref):
    # (block_n, d) x (block_v, d) → (block_n, block_v) fp32 on the MXU
    return jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fwd_kernel(lab_ref, x_ref, w_ref, loss_ref, lse_ref, m_sc, l_sc,
                pick_sc, *, block_v):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        pick_sc[...] = jnp.zeros_like(pick_sc)

    s = _logits_block(x_ref, w_ref)
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    lab = lab_ref[...][:, :1]                       # (block_n, 1)
    pick_sc[...] += jnp.sum(
        jnp.where(col == lab, s, 0.0), axis=1, keepdims=True)

    m_prev = m_sc[...]
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_cur)
    l_sc[...] = l_sc[...] * alpha + jnp.sum(
        jnp.exp(s - m_cur[:, :1]), axis=1, keepdims=True)
    m_sc[...] = m_cur

    @pl.when(j == nv - 1)
    def _finalize():
        lse = m_sc[...] + jnp.log(l_sc[...])
        lse_ref[...] = lse
        valid = lab_ref[...][:, :1] >= 0            # ignored rows → 0 loss
        loss_ref[...] = jnp.where(valid, lse - pick_sc[...], 0.0)


def _dlogits(x_ref, w_ref, lab_ref, g_ref, lse_ref, j, block_v):
    """(p − onehot) · g for one logit tile, recomputed from the saved lse
    (g is pre-zeroed for ignored rows on the host)."""
    s = _logits_block(x_ref, w_ref)
    p = jnp.exp(s - lse_ref[...][:, :1])
    col = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    onehot = (col == lab_ref[...][:, :1]).astype(jnp.float32)
    return (p - onehot) * g_ref[...][:, :1]


def _dx_kernel(lab_ref, g_ref, x_ref, w_ref, lse_ref, dx_ref, dx_sc, *,
               block_v):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        dx_sc[...] = jnp.zeros_like(dx_sc)

    dl = _dlogits(x_ref, w_ref, lab_ref, g_ref, lse_ref, j, block_v)
    dx_sc[...] += jax.lax.dot(dl.astype(w_ref.dtype), w_ref[...],
                              preferred_element_type=jnp.float32)

    @pl.when(j == nv - 1)
    def _finalize():
        dx_ref[...] = dx_sc[...].astype(dx_ref.dtype)


def _dw_kernel(lab_ref, g_ref, x_ref, w_ref, lse_ref, dw_ref, dw_sc, *,
               block_v):
    i = pl.program_id(1)
    ni = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        dw_sc[...] = jnp.zeros_like(dw_sc)

    j = pl.program_id(0)
    dl = _dlogits(x_ref, w_ref, lab_ref, g_ref, lse_ref, j, block_v)
    dw_sc[...] += jax.lax.dot_general(
        dl.astype(x_ref.dtype), x_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == ni - 1)
    def _finalize():
        dw_ref[...] = dw_sc[...].astype(dw_ref.dtype)


def _pick_block_v(V: int, want: int) -> int:
    for bv in (want, 512, 384, 256, 128):
        if bv <= V and V % bv == 0 and bv % _LANES == 0:
            return bv
    raise ValueError(
        f"vocab {V} has no 128-multiple block divisor (pad the vocab "
        f"— GPT-3's 50304 = 131*384 is already padded for this)")


def _pad_rows(a, n_pad, fill=0):
    return jnp.pad(a, ((0, n_pad), (0, 0)) if a.ndim == 2
                   else ((0, n_pad),), constant_values=fill)


def _row_spec(block_n):
    return pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0))


def _fwd(x, w, lab2, block_n, block_v, interpret):
    n, d = x.shape
    V = w.shape[0]
    grid = (n // block_n, V // block_v)
    # ptlint: disable=PT009 -- the fused head never materializes the
    # (n, V) logits: every row block walks ALL vocab tiles (online
    # softmax), so w is re-read n/block_n times by design — that HBM
    # traffic is what buys the O(block) logit memory.
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v),
        grid=grid,
        in_specs=[
            _row_spec(block_n),
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
        ],
        out_specs=[_row_spec(block_n), _row_spec(block_n)],
        out_shape=[jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((n, _LANES), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_n, _LANES), jnp.float32)] * 3,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lab2, x, w)
    return loss[:, 0], lse


def _bwd(x, w, lab2, lse, g2, block_n, block_v, interpret):
    n, d = x.shape
    V = w.shape[0]
    row = _row_spec(block_n)
    # ptlint: disable=PT009 -- dx rebuilds softmax tiles from scratch:
    # w is re-streamed per row block exactly like the forward walk.
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, block_v=block_v),
        grid=(n // block_n, V // block_v),
        in_specs=[
            row, row,
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            row,
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lab2, g2, x, w, lse)

    rowT = pl.BlockSpec((block_n, _LANES), lambda j, i: (i, 0))
    # ptlint: disable=PT009 -- dw walks every row block per vocab tile
    # (the transposed online-softmax recomputation); x re-reads scale
    # with V/block_v, inherent to not materializing logits.
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, block_v=block_v),
        grid=(V // block_v, n // block_n),
        in_specs=[
            rowT, rowT,
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            rowT,
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((V, d), w.dtype),
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lab2, g2, x, w, lse)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_ce(x, w, lab2, block_n, block_v, interpret):
    loss, _ = _fwd(x, w, lab2, block_n, block_v, interpret)
    return loss


def _fused_ce_fwd(x, w, lab2, block_n, block_v, interpret):
    loss, lse = _fwd(x, w, lab2, block_n, block_v, interpret)
    return loss, (x, w, lab2, lse)


def _fused_ce_bwd(block_n, block_v, interpret, res, dloss):
    import numpy as np
    x, w, lab2, lse = res
    # zero the cotangent on ignored rows so (p − onehot)·g vanishes there
    g = jnp.where(lab2[:, 0] >= 0, dloss.astype(jnp.float32), 0.0)
    g2 = jnp.broadcast_to(g[:, None], (g.shape[0], _LANES))
    dx, dw = _bwd(x, w, lab2, lse, g2, block_n, block_v, interpret)
    return dx, dw, np.zeros(lab2.shape, jax.dtypes.float0)


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_softmax_cross_entropy(x, w, labels, block_n: int = 128,
                                block_v: int = 512, interpret=None):
    """Per-row CE of ``softmax(x @ w.T)`` against ``labels`` without
    materializing the (N, V) logits.

    Args:
      x: (N, d) final hidden rows (post head-LN).
      w: (V, d) projection in embedding layout (tied wte feeds directly).
      labels: (N,) int32; negative labels are ignored (0 loss, 0 grad) —
        the shifted-causal-LM padding convention.
      block_n / block_v: logit tile streamed through VMEM; block_v is
        shrunk to a 128-multiple divisor of V (ValueError if none exists).
      interpret: defaults to True off-TPU so tests run on CPU.

    Returns (N,) fp32 per-row losses. Differentiable in x and w.
    """
    x, w = jnp.asarray(x), jnp.asarray(w)
    n, d = x.shape
    V = w.shape[0]
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    bv = _pick_block_v(V, block_v)
    if block_n % 8:
        raise ValueError(f"block_n must be a multiple of 8, got {block_n}")
    bn = block_n
    n_pad = (n + bn - 1) // bn * bn - n
    labels = jnp.asarray(labels, jnp.int32)
    if n_pad:
        x = _pad_rows(x, n_pad)
        labels = _pad_rows(labels, n_pad, fill=-1)
    lab2 = jnp.broadcast_to(labels[:, None], (labels.shape[0], _LANES))
    # ptlint: disable=PT001 -- interpret is a static Python flag
    loss = _fused_ce(x, w, lab2, bn, bv, bool(interpret))
    return loss[:n] if n_pad else loss


def ptgeom_cases():
    """Geometry registry for tools/ptgeom.py (ISSUE 20): head shapes
    from the bench ladder x logit-tile candidates, forward and
    backward, under jax.eval_shape."""
    from paddle_tpu.analysis import kernelmodel as km

    def case(geom, bn, bv, bwd=False):
        p = km.LADDER[geom]
        n = 64 if geom == "tiny" else 2048
        x = km.sds((n, p["dm"]), p["dtype"])
        w = km.sds((p["vocab"], p["dm"]), p["dtype"])
        lab = km.sds((n,), "int32")

        def run():
            import jax as _jax

            def loss(x, w, lab):
                l = fused_softmax_cross_entropy(x, w, lab, block_n=bn,
                                                block_v=bv)
                return jnp.sum(jnp.asarray(l, jnp.float32))

            fn = _jax.grad(loss, argnums=(0, 1)) if bwd else loss
            _jax.eval_shape(fn, x, w, lab)
        return km.GeomCase(
            kernel="fused_ce", geometry=geom,
            config=f"bn{bn}.bv{bv}" + (".bwd" if bwd else ""), run=run)

    cases = [case("tiny", 128, 512)]
    for geom in ("350m", "r06"):
        for bn, bv in ((128, 512), (256, 512)):
            cases.append(case(geom, bn, bv))
        cases.append(case(geom, 128, 512, bwd=True))
    return cases
