"""Fused bias + dropout + residual-add + LayerNorm as a Pallas TPU kernel.

Reference analog: paddle/fluid/operators/fused/fused_layernorm_residual_
dropout_bias.h and fused_bias_dropout_residual_layer_norm_op.cu — the CUDA
fusion that computes ``ln(residual + dropout(x + bias))`` in one kernel so
the intermediate (B, S, D) tensors never round-trip HBM. The TPU-native
re-design is one Pallas pass per row-block: load x once, apply bias +
counter-based dropout + residual in VMEM, compute row statistics in fp32,
and write the normalized output plus the pre-norm sum (the residual stream
a pre-LN transformer block carries forward).

The backward is a custom VJP in plain XLA: it regenerates the dropout mask
from the same counter PRF (zero residual memory, ≙ the Philox replay in
the CUDA backward) and recomputes x̂ from the saved (mean, rstd) row
statistics. The forward fusion is where the HBM win is; the backward
reductions (dγ/dβ are column sums over all rows) are XLA's home turf.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_layer_norm", "dropout_keep_mask"]

_LANES = 128


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _mix(x):
    """lowbias32 integer hash (same PRF family as the flash-attention
    dropout, so forward and backward regenerate identical masks)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def dropout_keep_mask(seed, row0, n_cols, block_shape, rate):
    """Deterministic keep-mask for a (rows, cols) block whose first row is
    ``row0`` of the global (M, N) tensor. Pure jnp: runs identically inside
    the Pallas kernel and in the XLA backward."""
    rows, cols = block_shape
    r = row0 + jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
    lin = r.astype(jnp.uint32) * jnp.uint32(n_cols) + c.astype(jnp.uint32)
    h = _mix(_mix(lin ^ jnp.asarray(seed).astype(jnp.uint32)))
    thresh = jnp.uint32(min(int(rate * 2.0**32), 2**32 - 1))
    return h >= thresh


def _fwd_kernel(seed_ref, x_ref, gamma_ref, beta_ref, *refs, eps, has_bias,
                has_residual, dropout_rate, block_m, n):
    idx = 0
    bias_ref = refs[idx] if has_bias else None
    idx += int(has_bias)
    res_ref = refs[idx] if has_residual else None
    idx += int(has_residual)
    y_ref, pre_ref, mean_ref, rstd_ref = refs[idx:idx + 4]

    i = pl.program_id(0)
    pre = x_ref[...].astype(jnp.float32)
    if has_bias:
        pre = pre + bias_ref[...].astype(jnp.float32)
    if dropout_rate > 0.0:
        keep = dropout_keep_mask(seed_ref[0], i * block_m, n, pre.shape,
                                 dropout_rate)
        pre = jnp.where(keep, pre / (1.0 - dropout_rate), 0.0)
    if has_residual:
        pre = pre + res_ref[...].astype(jnp.float32)

    mean = jnp.mean(pre, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(pre - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (pre - mean) * rstd
    y = xhat * gamma_ref[...].astype(jnp.float32) \
        + beta_ref[...].astype(jnp.float32)

    y_ref[...] = y.astype(y_ref.dtype)
    pre_ref[...] = pre.astype(pre_ref.dtype)
    # row stats are broadcast across the padded lane dim (TPU wants a
    # 128-lane minor); column 0 is the value
    mean_ref[...] = jnp.broadcast_to(mean, mean_ref.shape)
    rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _fwd_pallas(x2, gamma, beta, bias, residual, seed, eps, dropout_rate,
                interpret):
    m, n = x2.shape
    block_m = max(8, min(128, _round_up(m, 8)))
    m_pad = _round_up(m, block_m)
    if m_pad != m:
        pad = ((0, m_pad - m), (0, 0))
        x2 = jnp.pad(x2, pad)
        if residual is not None:
            residual = jnp.pad(residual, pad)
    row_spec = pl.BlockSpec((block_m, n), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, n), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((block_m, _LANES), lambda i: (i, 0))
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM),
                row_spec, vec_spec, vec_spec]
    args = [seed, x2, gamma.reshape(1, n), beta.reshape(1, n)]
    if bias is not None:
        in_specs.append(vec_spec)
        args.append(bias.reshape(1, n))
    if residual is not None:
        in_specs.append(row_spec)
        args.append(residual)
    kernel = functools.partial(
        _fwd_kernel, eps=eps, has_bias=bias is not None,
        has_residual=residual is not None, dropout_rate=dropout_rate,
        block_m=block_m, n=n)
    y, pre, mean, rstd = pl.pallas_call(
        kernel,
        grid=(m_pad // block_m,),
        in_specs=in_specs,
        out_specs=[row_spec, row_spec, stat_spec, stat_spec],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, n), x2.dtype),
            jax.ShapeDtypeStruct((m_pad, n), x2.dtype),
            jax.ShapeDtypeStruct((m_pad, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)
    return y[:m], pre[:m], mean[:m, :1], rstd[:m, :1]


def _fwd_xla(x2, gamma, beta, bias, residual, seed, eps, dropout_rate):
    pre = x2.astype(jnp.float32)
    if bias is not None:
        pre = pre + bias.astype(jnp.float32)
    if dropout_rate > 0.0:
        keep = dropout_keep_mask(seed[0], 0, x2.shape[1], pre.shape,
                                 dropout_rate)
        pre = jnp.where(keep, pre / (1.0 - dropout_rate), 0.0)
    if residual is not None:
        pre = pre + residual.astype(jnp.float32)
    mean = jnp.mean(pre, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(pre - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = ((pre - mean) * rstd * gamma.astype(jnp.float32)
         + beta.astype(jnp.float32))
    return (y.astype(x2.dtype), pre.astype(x2.dtype), mean, rstd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _fused_ln(x2, gamma, beta, bias, residual, seed, eps, dropout_rate,
              interpret):
    (y, pre), _ = _fused_ln_fwd(x2, gamma, beta, bias, residual, seed,
                                eps, dropout_rate, interpret)
    return y, pre


def _fused_ln_fwd(x2, gamma, beta, bias, residual, seed, eps, dropout_rate,
                  interpret):
    use_pallas = ((jax.default_backend() == "tpu" or interpret)
                  and x2.shape[1] % _LANES == 0)
    if use_pallas:
        y, pre, mean, rstd = _fwd_pallas(x2, gamma, beta, bias, residual,
                                         seed, eps, dropout_rate, interpret)
    else:
        y, pre, mean, rstd = _fwd_xla(x2, gamma, beta, bias, residual,
                                      seed, eps, dropout_rate)
    return (y, pre), (pre, mean, rstd, gamma, seed,
                      bias is not None, residual is not None)


def _fused_ln_bwd(eps, dropout_rate, interpret, res, cts):
    pre, mean, rstd, gamma, seed, has_bias, has_residual = res
    dy, dpre_out = cts
    n = pre.shape[1]
    pre_f = pre.astype(jnp.float32)
    dy_f = dy.astype(jnp.float32)
    xhat = (pre_f - mean) * rstd

    dgamma = jnp.sum(dy_f * xhat, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(dy_f, axis=0).astype(gamma.dtype)

    # LN input grad: rstd * (dxhat - mean(dxhat) - xhat * mean(dxhat·xhat))
    dxhat = dy_f * gamma.astype(jnp.float32)
    dpre = rstd * (dxhat
                   - jnp.mean(dxhat, axis=-1, keepdims=True)
                   - xhat * jnp.mean(dxhat * xhat, axis=-1, keepdims=True))
    # the pre-norm sum is also an output (residual stream): its cotangent
    # joins at the sum node
    dpre = dpre + dpre_out.astype(jnp.float32)

    dresidual = dpre.astype(pre.dtype) if has_residual else None
    dx = dpre
    if dropout_rate > 0.0:
        keep = dropout_keep_mask(seed[0], 0, n, dpre.shape, dropout_rate)
        dx = jnp.where(keep, dpre / (1.0 - dropout_rate), 0.0)
    dbias = jnp.sum(dx, axis=0).astype(gamma.dtype) if has_bias else None
    dseed = np.zeros(seed.shape, jax.dtypes.float0)
    return (dx.astype(pre.dtype), dgamma, dbeta, dbias, dresidual, dseed)


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm(x, gamma, beta, residual=None, bias=None,
                     dropout_p: float = 0.0, dropout_seed=None,
                     epsilon: float = 1e-5, interpret=None):
    """``ln(residual + dropout(x + bias))`` in one fused pass.

    Returns ``(y, pre)`` where ``pre`` is the pre-norm sum
    (≙ fused_layernorm_residual_dropout_bias.h returning both out and
    dropout_residual_out). Normalization is over the last dim; leading
    dims are flattened. Differentiable w.r.t. x/gamma/beta/bias/residual;
    dropout replays deterministically from ``dropout_seed`` (scalar int32,
    array or python int) in the backward — no mask is stored.
    ``interpret`` defaults to True off-TPU so tests run on CPU.
    """
    x = jnp.asarray(x)
    shape = x.shape
    n = shape[-1]
    x2 = x.reshape(-1, n)
    res2 = None if residual is None else jnp.asarray(residual).reshape(-1, n)
    if dropout_p >= 1.0 or dropout_p < 0.0:
        raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    seed = jnp.reshape(
        jnp.asarray(0 if dropout_seed is None else dropout_seed,
                    jnp.int32), (1,))
    y, pre = _fused_ln(x2, jnp.asarray(gamma), jnp.asarray(beta),
                       None if bias is None else jnp.asarray(bias),
                       res2, seed, float(epsilon), float(dropout_p),
                       bool(interpret))
    return y.reshape(shape), pre.reshape(shape)


def ptgeom_cases():
    """Geometry registry for tools/ptgeom.py (ISSUE 20); the pallas
    path needs n % 128 == 0, so only the 350m/r06 rungs apply."""
    from paddle_tpu.analysis import kernelmodel as km

    def case(geom):
        p = km.LADDER[geom]
        x = km.sds((2048, p["dm"]), p["dtype"])
        g = km.sds((p["dm"],), p["dtype"])

        def run():
            import jax as _jax
            _jax.eval_shape(fused_layer_norm, x, g, g)
        return km.GeomCase(kernel="fused_layer_norm", geometry=geom,
                           config="bm-auto", run=run)

    return [case("350m"), case("r06")]
