"""Kernel block-size autotuning with a persistent per-shape cache.

Reference analog: paddle/phi/kernels/autotune/auto_tune_base.h (TuneBase —
measure every candidate kernel config on the real shapes, pick the
fastest) + autotune/cache.cc (AutoTuneCache — per-(kernel, shape-key)
result cache so tuning happens once). The TPU twist: Pallas block sizes
are trace-time constants, so tuning must happen EAGERLY (outside jit) —
``tune(...)`` measures candidates on device, and kernels consult the
cache at trace time (a pure Python dict read) when no explicit block
size is passed.

The cache persists to ``~/.cache/paddle_tpu/autotune.json`` (override:
``PT_AUTOTUNE_CACHE``): the second process run hits the cache instead of
re-measuring, matching the reference's serialized cache behavior.
"""

import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["AutotuneCache", "get_cache", "tune"]


def _default_path() -> str:
    return os.environ.get(
        "PT_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "autotune.json"))


class AutotuneCache:
    """(kernel, shape-key) → best config (≙ cache.cc AutoTuneCache)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path if path is not None else _default_path()
        self._table: Dict[str, list] = {}
        self._loaded = False

    def _load(self):
        if self._loaded:
            return
        self._loaded = True
        try:
            with open(self.path) as f:
                self._table = json.load(f)
        except (OSError, ValueError):
            self._table = {}

    @staticmethod
    def key(kernel: str, **parts) -> str:
        return kernel + "|" + "|".join(
            f"{k}={parts[k]}" for k in sorted(parts))

    def get(self, key: str):
        self._load()
        hit = self._table.get(key)
        return tuple(hit) if isinstance(hit, list) else hit

    def put(self, key: str, config, persist: bool = True):
        self._load()
        self._table[key] = list(config) if isinstance(config, tuple) \
            else config
        if persist:
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                tmp = self.path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(self._table, f, indent=0, sort_keys=True)
                os.replace(tmp, self.path)
            except OSError:
                pass  # cache is an optimization; never fail the caller

    def clear(self):
        self._table = {}
        self._loaded = True


_GLOBAL: Optional[AutotuneCache] = None


def get_cache() -> AutotuneCache:
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = AutotuneCache()
    return _GLOBAL


def tune(kernel: str, key: str, candidates: Sequence,
         build_and_run: Callable, warmup: int = 1, iters: int = 3,
         cache: Optional[AutotuneCache] = None,
         geom_check: Optional[Callable] = None):
    """Measure every candidate config and cache the argmin
    (≙ auto_tune_base.h TuneBase::PickBestKernel).

    ``build_and_run(config)`` must execute the kernel end-to-end on the
    real shapes and block until the result is ready. Configs that raise
    (e.g. a block shape Mosaic rejects for this dtype) are skipped.
    ``geom_check(config)`` (ISSUE 20) is consulted FIRST: a truthy
    return is a static refusal reason (e.g. ptgeom's PT006 VMEM budget)
    and the candidate is skipped without ever being built or timed —
    chip-time sweeps stop burning iterations on geometries that cannot
    fit. Returns (best_config, {config: seconds}); the winner lands in
    the cache keyed by ``key``.
    """
    cache = cache or get_cache()
    hit = cache.get(key)
    if hit is not None:
        return hit, {}
    timings: Dict = {}
    refused: Dict = {}
    last_exc = None
    for config in candidates:
        ckey = tuple(config) if isinstance(config, (list, tuple)) \
            else config
        if geom_check is not None:
            try:
                reason = geom_check(config)
            except Exception:  # a broken guard must not block tuning
                reason = None
            if reason:
                refused[ckey] = str(reason)
                continue
        try:
            build_and_run(config)  # compile + first run
            for _ in range(warmup):
                build_and_run(config)
            t0 = time.perf_counter()
            for _ in range(iters):
                build_and_run(config)
            timings[ckey] = (time.perf_counter() - t0) / iters
        except Exception as e:  # a config the backend rejects is skipped
            last_exc = e
            continue
    if not timings:
        detail = ""
        if refused:
            detail = "; geometry-refused: " + "; ".join(
                f"{k}: {v}" for k, v in refused.items())
        raise ValueError(f"autotune({kernel}): every candidate failed "
                         f"for key {key}{detail}") from last_exc
    best = min(timings, key=timings.get)
    cache.put(key, best)
    return best, timings
