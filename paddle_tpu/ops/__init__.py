from paddle_tpu.ops.registry import OpSpec, register_op, get_op, all_ops
