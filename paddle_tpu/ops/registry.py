"""Single-source-of-truth op registry.

Reference analog: the YAML op specs (paddle/phi/api/yaml/ops.yaml) from which
the reference generates its C++ API, grad nodes, and Python bindings
(api_gen.py / eager_gen.py / python_c_gen.py). Here there is no codegen to do —
ops are pure jax functions and autodiff comes from tracing — so the registry's
job is metadata: a numpy oracle per op for the OpTest harness
(ref: python/paddle/fluid/tests/unittests/op_test.py:333), a category, and the
reference citation. Tests iterate ``all_ops()`` and check eager vs jit vs the
numpy oracle on every op that declares one.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


@dataclass
class OpSpec:
    name: str
    fn: Callable
    category: str
    np_ref: Optional[Callable] = None       # numpy oracle
    sample_args: Optional[Callable] = None  # () -> (args, kwargs) for OpTest
    ref: str = ""                           # reference file:line citation
    differentiable: bool = True
    test_fn: Optional[Callable] = None      # harness adapter when fn's raw
    # signature/output doesn't fit the oracle comparison (tuple outputs,
    # string args, list inputs); wraps fn, never replaces it
    jit_ok: bool = True                     # False for host-side dynamic-
    # shape ops (masked_select/unique/eig...) that cannot trace
    alias_of: Optional[str] = None          # inplace-suffix aliases: same
    # fn object as the base op; OpTest covers the base, a fn-identity test
    # covers the alias (re-running the oracle would only duplicate runtime)


_OPS: Dict[str, OpSpec] = {}


def register_op(name: str, fn: Callable, category: str,
                np_ref: Optional[Callable] = None,
                sample_args: Optional[Callable] = None,
                ref: str = "", differentiable: bool = True,
                test_fn: Optional[Callable] = None,
                jit_ok: bool = True) -> Callable:
    _OPS[name] = OpSpec(name, fn, category, np_ref, sample_args, ref,
                        differentiable, test_fn, jit_ok)
    return fn


def _ensure_oracles() -> None:
    """Attach the numpy oracles (ops/oracles.py) on first registry read.

    The oracle table is part of the op registry proper — every op's spec is
    incomplete without its ``np_ref``/``sample_args`` (ref: op_test.py:333
    pairs every op with its numpy check) — but it imports the whole Python
    surface, so it attaches lazily on first introspection rather than at
    package-import time. attach_all() itself is idempotent.
    """
    from paddle_tpu.ops import oracles

    oracles.attach_all()


def get_op(name: str) -> OpSpec:
    _ensure_oracles()
    return _OPS[name]


def all_ops() -> List[OpSpec]:
    _ensure_oracles()
    return list(_OPS.values())
