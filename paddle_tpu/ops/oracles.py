"""Numpy oracles for every registered op that lacked one (VERDICT r2 item 2).

Reference analog: the per-op ``OpTest`` subclasses under
python/paddle/fluid/tests/unittests/test_*_op.py (pattern op_test.py:333) —
one numpy oracle per op, checked across execution modes. Here the oracles
attach to the central registry after all op modules import, in one
table-driven pass; tests/test_op_suite.py iterates the registry.

Conventions:
- ``sample()`` returns ``(args, kwargs)``; the harness calls
  ``fn(*args, **kwargs)`` and ``np_ref(*map(np.asarray, args))`` — so the
  oracle closes over the same kwargs.
- ``test_fn`` adapts ops whose raw signature/output can't be compared
  directly (tuple outputs → values only; list/str arguments → closed over).
- Random ops get no value oracle; tests/test_op_suite.py checks their
  distributions statistically instead (listed in RANDOM_OPS there).
"""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops.registry import _OPS

_RS = np.random.RandomState(20260729)


def _f(*shape):
    return _RS.randn(*shape).astype(np.float32) if shape else \
        np.float32(_RS.randn())


def _pos(*shape):
    return (np.abs(_RS.randn(*shape)) + 0.5).astype(np.float32)


def _i(hi, *shape):
    return _RS.randint(0, hi, shape).astype(np.int32)


def _spd(n):
    a = _RS.randn(n, n).astype(np.float32)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


def _attach(name, np_ref, sample_args_value, test_fn=None,
            differentiable=None, jit_ok=None):
    spec = _OPS[name]
    spec.np_ref = np_ref
    spec.sample_args = lambda v=sample_args_value: v
    if test_fn is not None:
        spec.test_fn = test_fn
    if differentiable is not None:
        spec.differentiable = differentiable
    if jit_ok is not None:
        spec.jit_ok = jit_ok


_ATTACHED = False


def attach_all():
    global _ATTACHED
    if _ATTACHED:
        return
    _ATTACHED = True
    import paddle_tpu.tensor as T

    x45 = _f(4, 5)
    x345 = _f(3, 4, 5)
    x44 = _f(4, 4)
    spd4 = _spd(4)

    # -- math leftovers ----------------------------------------------------
    _attach("cumprod", lambda x: np.cumprod(x, axis=1), ((x45,), {"dim": 1}))
    _attach("logcumsumexp",
            lambda x: np.log(np.cumsum(np.exp(x.astype(np.float64)),
                                       axis=-1)).astype(np.float32),
            ((x45,), {}))
    _attach("lerp", lambda x, y: x + 0.3 * (y - x),
            ((x45, _f(4, 5)), {"weight": 0.3}))
    _attach("addmm", lambda i, x, y: 0.5 * i + 2.0 * (x @ y),
            ((_f(4, 3), _f(4, 5), _f(5, 3)), {"beta": 0.5, "alpha": 2.0}))
    mplex_idx = _i(2, 4, 1)
    _attach("multiplex",
            lambda a, b: np.where(mplex_idx.reshape(-1, 1) == 0, a, b),
            ((x45, _f(4, 5)), {}),
            test_fn=lambda a, b: T.multiplex([a, b], jnp.asarray(mplex_idx)))
    _attach("outer", np.outer, ((_f(4), _f(5)), {}))
    take_idx = _i(20, 7)
    _attach("take", lambda x: x.reshape(-1)[take_idx],
            ((x45,), {}),
            test_fn=lambda x: T.take(x, jnp.asarray(take_idx)))

    # -- manipulation ------------------------------------------------------
    _attach("reshape", lambda x: x.reshape(5, 4), ((x45,), {"shape": (5, 4)}))
    _attach("flatten", lambda x: x.reshape(3, 20),
            ((x345,), {"start_axis": 1, "stop_axis": 2}))
    _attach("transpose", lambda x: x.transpose(2, 0, 1),
            ((x345,), {"perm": (2, 0, 1)}))
    _attach("moveaxis", lambda x: np.moveaxis(x, 0, 2),
            ((x345,), {"source": 0, "destination": 2}))
    _attach("swapaxes", lambda x: np.swapaxes(x, 0, 1),
            ((x345,), {"axis1": 0, "axis2": 1}))
    _attach("squeeze", lambda x: np.squeeze(x, 1),
            ((_f(3, 1, 5),), {"axis": 1}))
    _attach("unsqueeze", lambda x: x[:, None], ((x45,), {"axis": 1}))
    _attach("concat", lambda a, b: np.concatenate([a, b], axis=1),
            ((x45, _f(4, 3)), {}),
            test_fn=lambda a, b: T.concat([a, b], axis=1))
    _attach("stack", lambda a, b: np.stack([a, b], axis=1),
            ((x45, _f(4, 5)), {}),
            test_fn=lambda a, b: T.stack([a, b], axis=1))
    _attach("unstack", lambda x: x.transpose(1, 0, 2), ((x345,), {}),
            test_fn=lambda x: jnp.stack(T.unstack(x, axis=1)))
    _attach("split", lambda x: np.stack(np.split(x, 2, axis=0)),
            ((x44,), {}),
            test_fn=lambda x: jnp.stack(T.split(x, 2, axis=0)))
    _attach("chunk", lambda x: np.stack(np.split(x, 2, axis=1)),
            ((x44,), {}),
            test_fn=lambda x: jnp.stack(T.chunk(x, 2, axis=1)))
    _attach("tile", lambda x: np.tile(x, (2, 3)),
            ((x45,), {"repeat_times": (2, 3)}))
    _attach("repeat_interleave", lambda x: np.repeat(x, 2, axis=1),
            ((x45,), {"repeats": 2, "axis": 1}))
    _attach("expand", lambda x: np.broadcast_to(x, (3, 4, 5)),
            ((_f(1, 4, 5),), {"shape": (3, 4, 5)}))
    _attach("expand_as", lambda x, y: np.broadcast_to(x, y.shape),
            ((_f(1, 5), _f(4, 5)), {}))
    _attach("broadcast_to", lambda x: np.broadcast_to(x, (6, 4, 5)),
            ((x45,), {"shape": (6, 4, 5)}))
    _attach("broadcast_tensors",
            lambda a, b: np.stack(np.broadcast_arrays(a, b)),
            ((_f(1, 5), _f(4, 1)), {}),
            test_fn=lambda a, b: jnp.stack(T.broadcast_tensors([a, b])),
            differentiable=False)
    _attach("flip", lambda x: np.flip(x, (0, 1)), ((x45,), {"axis": (0, 1)}))
    _attach("roll", lambda x: np.roll(x, 2, axis=1),
            ((x45,), {"shifts": 2, "axis": 1}))
    g_idx = _i(4, 6)
    _attach("gather", lambda x: x[g_idx], ((x45,), {}),
            test_fn=lambda x: T.gather(x, jnp.asarray(g_idx), axis=0))
    gnd_idx = _i(3, 5, 2)
    _attach("gather_nd", lambda x: x[gnd_idx[:, 0], gnd_idx[:, 1]],
            ((x345[:3, :3],), {}),
            test_fn=lambda x: T.gather_nd(x, jnp.asarray(gnd_idx)))
    sc_idx = np.array([0, 2, 3], np.int32)

    def _scatter_np(x, u):
        out = x.copy()
        out[sc_idx] = u
        return out
    _attach("scatter", _scatter_np, ((x45, _f(3, 5)), {}),
            test_fn=lambda x, u: T.scatter(x, jnp.asarray(sc_idx), u))
    snd_idx = np.array([[1], [3]], np.int32)

    def _scatter_nd_np(u):
        out = np.zeros((6, 5), np.float32)
        np.add.at(out, snd_idx[:, 0], u)
        return out
    _attach("scatter_nd", _scatter_nd_np, ((_f(2, 5),), {}),
            test_fn=lambda u: T.scatter_nd(jnp.asarray(snd_idx), u, (6, 5)))

    def _scatter_nd_add_np(x, u):
        out = x.copy()
        np.add.at(out, snd_idx[:, 0], u)
        return out
    _attach("scatter_nd_add", _scatter_nd_add_np, ((x45, _f(2, 5)), {}),
            test_fn=lambda x, u: T.scatter_nd_add(x, jnp.asarray(snd_idx), u))
    pa_idx = _i(4, 4, 5)
    _attach("put_along_axis",
            lambda x, v: _put_ref(x, pa_idx, v),
            ((x45, _f(4, 5)), {}),
            test_fn=lambda x, v: T.put_along_axis(
                x, jnp.asarray(pa_idx), v, axis=0))
    _attach("take_along_axis",
            lambda x: np.take_along_axis(x, pa_idx.astype(np.int64), 0),
            ((x45,), {}),
            test_fn=lambda x: T.take_along_axis(x, jnp.asarray(pa_idx),
                                                axis=0))
    is_idx = _i(4, 6)
    _attach("index_select", lambda x: x[is_idx], ((x45,), {}),
            test_fn=lambda x: T.index_select(x, jnp.asarray(is_idx), axis=0))
    ismp_idx = _i(5, 4, 3)
    _attach("index_sample",
            lambda x: np.take_along_axis(x, ismp_idx.astype(np.int64), 1),
            ((x45,), {}),
            test_fn=lambda x: T.index_sample(x, jnp.asarray(ismp_idx)))
    ia_idx = np.array([0, 2], np.int32)

    def _index_add_np(x, v):
        out = x.copy()
        np.add.at(out, ia_idx, v)
        return out
    _attach("index_add", _index_add_np, ((x45, _f(2, 5)), {}),
            test_fn=lambda x, v: T.index_add(x, jnp.asarray(ia_idx), 0, v))
    msk = _RS.rand(4, 5) > 0.5
    _attach("masked_select", lambda x: x[msk], ((x45,), {}),
            test_fn=lambda x: T.masked_select(x, jnp.asarray(msk)),
            jit_ok=False, differentiable=False)
    _attach("masked_fill", lambda x: np.where(msk, 9.0, x), ((x45,), {}),
            test_fn=lambda x: T.masked_fill(x, jnp.asarray(msk), 9.0))
    _attach("where", lambda c, x, y: np.where(c, x, y),
            ((msk, x45, _f(4, 5)), {}), differentiable=False)
    nz = (_RS.rand(4, 5) > 0.6).astype(np.float32)
    _attach("nonzero", lambda x: np.stack(np.nonzero(x), axis=1),
            ((nz,), {}), jit_ok=False, differentiable=False)
    _attach("pad",
            lambda x: np.pad(x, [(0, 0), (0, 0), (3, 4), (1, 2)]),
            ((_f(2, 3, 4, 5),), {"pad": [1, 2, 3, 4]}))
    uq = _i(5, 20).astype(np.float32)
    _attach("unique", lambda x: np.unique(x), ((uq,), {}),
            jit_ok=False, differentiable=False)
    ucq = np.array([1, 1, 2, 2, 2, 3, 1, 1], np.float32)
    _attach("unique_consecutive",
            lambda x: np.array([1, 2, 3, 1], np.float32), ((ucq,), {}),
            jit_ok=False, differentiable=False)
    cplx = _f(4, 6, 2)
    _attach("as_complex", lambda x: x[..., 0] + 1j * x[..., 1],
            ((cplx,), {}), differentiable=False)
    zc = (cplx[..., 0] + 1j * cplx[..., 1]).astype(np.complex64)
    _attach("as_real",
            lambda z: np.stack([z.real, z.imag], axis=-1), ((zc,), {}),
            differentiable=False)
    _attach("real", np.real, ((zc,), {}), differentiable=False)
    _attach("imag", np.imag, ((zc,), {}), differentiable=False)
    _attach("cast", lambda x: x.astype(np.int32),
            ((x45 * 10,), {"dtype": "int32"}), differentiable=False)
    _attach("crop", lambda x: x[1:3, 2:5],
            ((x45,), {"shape": (2, 3), "offsets": (1, 2)}))
    _attach("strided_slice", lambda x: x[:, 1:5:2],
            ((x45,), {"axes": [1], "starts": [1], "ends": [5],
                      "strides": [2]}))
    _attach("slice", lambda x: x[:, 1:4],
            ((x45,), {"axes": [1], "starts": [1], "ends": [4]}))
    shard_in = _i(20, 8)

    def _shard_index_np(idx):
        # index_num=20, nshards=2, shard_id=0 → ids in [0,10) map to
        # local id, others to ignore_value -1
        size = 20 // 2
        ok = (idx >= 0) & (idx < size)
        return np.where(ok, idx - 0 * size, -1).astype(idx.dtype)
    _attach("shard_index", _shard_index_np, ((shard_in,), {
        "index_num": 20, "nshards": 2, "shard_id": 0}),
        differentiable=False)
    _attach("tensordot", lambda x, y: np.tensordot(x, y, axes=1),
            ((x45, _f(5, 3)), {"axes": 1}))
    _attach("diag", lambda x: np.diag(x, k=1), ((x44,), {"offset": 1}))
    _attach("diagflat", lambda x: np.diagflat(x, 1), ((_f(4),), {"offset": 1}))

    def _diag_embed_np(x):
        out = np.zeros(x.shape + (x.shape[-1],), x.dtype)
        ii = np.arange(x.shape[-1])
        out[..., ii, ii] = x
        return out
    _attach("diag_embed", _diag_embed_np, ((x45,), {}))
    _attach("tril", lambda x: np.tril(x, -1), ((x44,), {"diagonal": -1}))
    _attach("triu", lambda x: np.triu(x, 1), ((x44,), {"diagonal": 1}))
    _attach("meshgrid",
            lambda a, b: np.stack(np.meshgrid(a, b, indexing="ij")),
            ((_f(4), _f(5)), {}),
            test_fn=lambda a, b: jnp.stack(T.meshgrid(a, b)),
            differentiable=False)
    _attach("unbind", lambda x: x.transpose(1, 0, 2), ((x345,), {}),
            test_fn=lambda x: jnp.stack(T.unbind(x, axis=1)))
    _attach("numel", lambda x: np.asarray(x.size), ((x45,), {}),
            differentiable=False)
    _attach("shape", lambda x: np.asarray(x.shape), ((x345,), {}),
            differentiable=False)
    _attach("rank", lambda x: np.asarray(x.ndim), ((x345,), {}),
            differentiable=False)
    _attach("is_empty", lambda x: np.asarray(False), ((x45,), {}),
            differentiable=False)
    _attach("view", lambda x: x.reshape(5, 4),
            ((x45,), {"shape_or_dtype": (5, 4)}))
    _attach("view_as", lambda x, y: x.reshape(y.shape),
            ((x45, _f(5, 4)), {}))
    _attach("atleast_1d", lambda x: np.atleast_1d(x), ((_f(),), {}),
            differentiable=False)
    _attach("atleast_2d", lambda x: np.atleast_2d(x), ((_f(4),), {}),
            differentiable=False)
    _attach("atleast_3d", lambda x: np.atleast_3d(x), ((x45,), {}),
            differentiable=False)

    # -- creation ----------------------------------------------------------
    _attach("to_tensor", lambda x: x, ((x45,), {}))
    _attach("zeros", lambda: np.zeros((3, 4), np.float32),
            ((), {"shape": (3, 4)}), differentiable=False)
    _attach("ones", lambda: np.ones((3, 4), np.float32),
            ((), {"shape": (3, 4)}), differentiable=False)
    _attach("full", lambda: np.full((3, 4), 2.5, np.float32),
            ((), {"shape": (3, 4), "fill_value": 2.5}),
            differentiable=False)
    _attach("zeros_like", np.zeros_like, ((x45,), {}), differentiable=False)
    _attach("ones_like", np.ones_like, ((x45,), {}), differentiable=False)
    _attach("full_like", lambda x: np.full_like(x, 7.0),
            ((x45,), {"fill_value": 7.0}), differentiable=False)
    _attach("empty", lambda: np.zeros((3, 4), np.float32),
            ((), {"shape": (3, 4)}), differentiable=False)
    _attach("empty_like", np.zeros_like, ((x45,), {}), differentiable=False)
    _attach("arange", lambda: np.arange(2, 20, 3, dtype=np.float32),
            ((), {"start": 2, "end": 20, "step": 3, "dtype": "float32"}),
            differentiable=False)
    _attach("linspace", lambda: np.linspace(0, 1, 7, dtype=np.float32),
            ((), {"start": 0.0, "stop": 1.0, "num": 7}),
            differentiable=False)
    _attach("logspace",
            lambda: np.logspace(0, 2, 5, base=10.0, dtype=np.float32),
            ((), {"start": 0.0, "stop": 2.0, "num": 5}),
            differentiable=False)
    _attach("eye", lambda: np.eye(4, 6, dtype=np.float32),
            ((), {"num_rows": 4, "num_columns": 6}), differentiable=False)
    _attach("tril_indices", lambda: np.stack(np.tril_indices(4, -1, 5)),
            ((), {"row": 4, "col": 5, "offset": -1}), differentiable=False)
    _attach("triu_indices", lambda: np.stack(np.triu_indices(4, 1, 5)),
            ((), {"row": 4, "col": 5, "offset": 1}), differentiable=False)
    _attach("clone", lambda x: x, ((x45,), {}))
    _attach("assign", lambda x: x, ((x45,), {}))
    _attach("complex", lambda r, i: (r + 1j * i).astype(np.complex64),
            ((x45, _f(4, 5)), {}), differentiable=False)
    _attach("polar",
            lambda a, t: (a * np.exp(1j * t)).astype(np.complex64),
            ((_pos(4, 5), _f(4, 5)), {}), differentiable=False)
    oh_in = _i(6, 7)
    _attach("one_hot", lambda x: np.eye(6, dtype=np.float32)[x],
            ((oh_in,), {"num_classes": 6}), differentiable=False)

    # -- linalg ------------------------------------------------------------
    _attach("mm", np.matmul, ((x45, _f(5, 3)), {}))
    _attach("dot", lambda a, b: np.asarray(np.dot(a, b)),
            ((_f(5), _f(5)), {}))
    _attach("mv", lambda a, b: a @ b, ((x45, _f(5)), {}))
    _attach("cond", lambda x: np.asarray(np.linalg.cond(x), np.float32),
            ((spd4,), {}), differentiable=False)
    _attach("slogdet", lambda x: np.stack(np.linalg.slogdet(x)),
            ((spd4,), {}))
    _attach("pinv", lambda x: np.linalg.pinv(x, rcond=1e-15),
            ((x45,), {}), differentiable=False)
    _attach("solve", np.linalg.solve, ((spd4, _f(4, 3)), {}))
    tri_u = np.triu(_RS.randn(4, 4)).astype(np.float32) + 3 * np.eye(
        4, dtype=np.float32)
    _attach("triangular_solve",
            lambda a, b: np.linalg.solve(np.triu(a), b),
            ((tri_u, _f(4, 2)), {"upper": True}))
    _attach("cholesky", np.linalg.cholesky, ((spd4,), {}))
    chol_l = np.linalg.cholesky(_spd(4)).astype(np.float32)
    _attach("cholesky_solve",
            lambda b, L: np.linalg.solve(L @ L.T, b),
            ((_f(4, 2), chol_l), {"upper": False}))

    def _lu_recon(x):
        lu_mat, piv = T.lu(x)
        lu_mat = np.asarray(lu_mat)
        piv = np.asarray(piv)
        n = x.shape[0] if hasattr(x, "shape") else 4
        l = np.tril(lu_mat, -1) + np.eye(n, dtype=lu_mat.dtype)
        u = np.triu(lu_mat)
        a = l @ u
        # undo partial-pivot row swaps (LAPACK ipiv convention)
        for k in reversed(range(len(piv))):
            a[[k, piv[k]]] = a[[piv[k], k]]
        return jnp.asarray(a)
    _attach("lu", lambda x: x, ((spd4,), {}), test_fn=_lu_recon,
            jit_ok=False, differentiable=False)
    _attach("qr", lambda x: x, ((x45[:, :4],), {}),
            test_fn=lambda x: (lambda qr_: qr_[0] @ qr_[1])(T.qr(x)))
    _attach("svd", lambda x: np.linalg.svd(x, compute_uv=False),
            ((x45,), {}),
            test_fn=lambda x: T.svd(x)[1], differentiable=False)
    _attach("eig",
            lambda x: np.sort(np.abs(np.linalg.eigvals(x))),
            ((x44,), {}),
            test_fn=lambda x: jnp.sort(jnp.abs(T.eig(x)[0])),
            jit_ok=False, differentiable=False)
    _attach("eigh", lambda x: np.linalg.eigvalsh(x), ((spd4,), {}),
            test_fn=lambda x: T.eigh(x)[0], differentiable=False)
    _attach("eigvals",
            lambda x: np.sort(np.abs(np.linalg.eigvals(x))),
            ((x44,), {}),
            test_fn=lambda x: jnp.sort(jnp.abs(T.eigvals(x))),
            jit_ok=False, differentiable=False)
    _attach("eigvalsh", np.linalg.eigvalsh, ((spd4,), {}),
            differentiable=False)
    _attach("matrix_power", lambda x: np.linalg.matrix_power(x, 3),
            ((spd4 / 4.0,), {"n": 3}))
    _attach("matrix_rank",
            lambda x: np.asarray(np.linalg.matrix_rank(x)),
            ((x45,), {}), differentiable=False)
    _attach("multi_dot", lambda a, b, c: a @ b @ c,
            ((_f(3, 4), x45, _f(5, 2)), {}),
            test_fn=lambda a, b, c: T.multi_dot([a, b, c]),
            differentiable=False)
    _attach("histogram",
            lambda x: np.histogram(x, bins=10, range=(-3, 3))[0],
            ((x45,), {"bins": 10, "min": -3, "max": 3}),
            differentiable=False)
    bc_in = _i(6, 30)
    _attach("bincount", lambda x: np.bincount(x, minlength=8),
            ((bc_in,), {"minlength": 8}), differentiable=False,
            jit_ok=False)
    _attach("einsum", lambda a, b: np.einsum("ij,jk->ik", a, b),
            ((x45, _f(5, 3)), {}),
            test_fn=lambda a, b: T.einsum("ij,jk->ik", a, b))
    _attach("lstsq", lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0],
            ((_f(6, 4), _f(6, 2)), {}),
            test_fn=lambda a, b: T.lstsq(a, b)[0], differentiable=False)
    _attach("corrcoef", np.corrcoef, ((_f(4, 10),), {}),
            differentiable=False)
    _attach("cov", lambda x: np.cov(x, ddof=1), ((_f(4, 10),), {}))

    # -- logic -------------------------------------------------------------
    _attach("equal_all", lambda a, b: np.asarray(np.array_equal(a, b)),
            ((x45, x45.copy()), {}), differentiable=False)
    _attach("allclose",
            lambda a, b: np.asarray(np.allclose(a, b, rtol=1e-5, atol=1e-8)),
            ((x45, x45 + 1e-9), {}), differentiable=False)
    _attach("is_tensor", lambda x: np.asarray(True),
            ((jnp.asarray(x45),), {}), differentiable=False)

    # -- search ------------------------------------------------------------
    _attach("topk", lambda x: -np.sort(-x, axis=-1)[..., :3],
            ((x45,), {}),
            test_fn=lambda x: T.topk(x, k=3)[0])
    ss_seq = np.sort(_f(10))
    _attach("searchsorted", lambda s, v: np.searchsorted(s, v).astype(
        np.int64), ((ss_seq, _f(6)), {}), differentiable=False)
    _attach("kthvalue", lambda x: np.sort(x, axis=-1)[..., 1],
            ((x45,), {}),
            test_fn=lambda x: T.kthvalue(x, k=2)[0])
    md_in = _i(3, 4, 9).astype(np.float32)

    def _mode_np(x):
        out = np.empty(x.shape[0], x.dtype)
        for r in range(x.shape[0]):
            vals, cnt = np.unique(x[r], return_counts=True)
            # smallest value among the most frequent (scipy/torch tie rule)
            out[r] = vals[cnt == cnt.max()].min()
        return out
    _attach("mode", _mode_np, ((md_in,), {}),
            test_fn=lambda x: T.mode(x)[0], differentiable=False,
            jit_ok=False)
    if_idx = np.array([0, 3], np.int32)
    _attach("index_fill",
            lambda x: _index_fill_np(x, if_idx, 5.0), ((x45,), {}),
            test_fn=lambda x: T.index_fill(x, jnp.asarray(if_idx), 0, 5.0))
    _attach("bucketize", lambda x: np.searchsorted(ss_seq, x).astype(
        np.int64), ((_f(6),), {}),
        test_fn=lambda x: T.bucketize(x, jnp.asarray(ss_seq)),
        differentiable=False)

    # -- stat --------------------------------------------------------------
    # -- surface growth (r3): new ops registered this round ----------------
    _attach("vsplit", lambda x: np.stack(np.split(x, 2, 0)), ((x44,), {}),
            test_fn=lambda x: jnp.stack(T.vsplit(x, 2)))
    _attach("hsplit", lambda x: np.stack(np.split(x, 2, 1)), ((x44,), {}),
            test_fn=lambda x: jnp.stack(T.hsplit(x, 2)))
    _attach("dsplit", lambda x: np.stack(np.split(x, 2, 2)),
            ((_f(3, 4, 6),), {}),
            test_fn=lambda x: jnp.stack(T.dsplit(x, 2)))
    _attach("hstack", lambda a, b: np.hstack([a, b]),
            ((x45, _f(4, 3)), {}),
            test_fn=lambda a, b: T.hstack([a, b]))
    _attach("vstack", lambda a, b: np.vstack([a, b]),
            ((x45, _f(2, 5)), {}),
            test_fn=lambda a, b: T.vstack([a, b]))

    def _fill_diag_np(x):
        out = x.copy()
        np.fill_diagonal(out, 3.5)
        return out
    _attach("fill_diagonal", _fill_diag_np, ((x44,), {"value": 3.5}))

    def _fill_diag_t_np(x, y):
        out = x.copy()
        n = min(x.shape[0], x.shape[1] - 1)
        out[np.arange(n), np.arange(n) + 1] = y.reshape(-1)[:n]
        return out
    _attach("fill_diagonal_tensor", _fill_diag_t_np,
            ((x45, _f(4)), {"offset": 1}))
    _attach("tolist", lambda x: x, ((x45,), {}),
            test_fn=lambda x: jnp.asarray(T.tolist(x)),
            jit_ok=False, differentiable=False)
    _attach("add_n", lambda a, b, c: a + b + c,
            ((x45, _f(4, 5), _f(4, 5)), {}),
            test_fn=lambda a, b, c: T.add_n([a, b, c]))
    _attach("dist", lambda a, b: np.asarray(
        np.sqrt(((a - b) ** 2).sum()), np.float32),
        ((x45, _f(4, 5)), {"p": 2}))
    _attach("frexp", lambda x: np.frexp(x)[0], ((_pos(4, 5),), {}),
            test_fn=lambda x: T.frexp(x)[0], differentiable=False)
    _attach("inverse", np.linalg.inv, ((spd4,), {}))
    _attach("renorm",
            lambda x: x * np.minimum(
                1.0, 1.5 / (np.abs(x ** 2).sum(
                    axis=(1,), keepdims=True) ** 0.5 + 1e-7)),
            ((x45,), {"p": 2, "axis": 0, "max_norm": 1.5}))
    _attach("trapezoid", lambda y: np.trapezoid(y, dx=0.5, axis=-1)
            if hasattr(np, "trapezoid") else np.trapz(y, dx=0.5, axis=-1),
            ((x45,), {"dx": 0.5}))
    _attach("broadcast_shape", lambda: np.array([4, 5]), ((), {}),
            test_fn=lambda: jnp.asarray(T.broadcast_shape((4, 1), (1, 5))),
            differentiable=False, jit_ok=False)
    _attach("is_complex", lambda x: np.asarray(False), ((x45,), {}),
            differentiable=False, jit_ok=False)
    _attach("is_floating_point", lambda x: np.asarray(True), ((x45,), {}),
            differentiable=False, jit_ok=False)
    _attach("is_integer", lambda x: np.asarray(False), ((x45,), {}),
            differentiable=False, jit_ok=False)

    def _lu_unpack_recon(x):
        lu_mat, piv = T.lu(x)
        p, l, u = T.lu_unpack(lu_mat, piv)
        return p @ l @ u
    _attach("lu_unpack", lambda x: x, ((spd4,), {}),
            test_fn=_lu_unpack_recon, jit_ok=False, differentiable=False)
    _attach("vander", lambda x: np.vander(x, 4), ((_f(5),), {"n": 4}))

    # -- stat --------------------------------------------------------------
    _attach("quantile", lambda x: np.quantile(
        x.astype(np.float64), 0.3, axis=1).astype(np.float32),
        ((x45,), {"q": 0.3, "axis": 1}))
    nanq = x45.copy()
    nanq[0, 0] = np.nan
    _attach("nanquantile", lambda x: np.nanquantile(
        x.astype(np.float64), 0.7, axis=1).astype(np.float32),
        ((nanq,), {"q": 0.7, "axis": 1}), differentiable=False)


def _put_ref(x, idx, v):
    out = x.copy()
    np.put_along_axis(out, idx.astype(np.int64), v, 0)
    return out


def _index_fill_np(x, idx, value):
    out = x.copy()
    out[idx] = value
    return out
