"""Dtype aliases and the default-dtype policy.

Reference analog: phi::DataType (paddle/phi/common/data_type.h) and
paddle.set_default_dtype (python/paddle/framework/framework.py).
On TPU the preferred compute dtype is bfloat16; float32 stays the default
for parameter math unless the user opts in via AMP (paddle_tpu.amp).
"""

import jax.numpy as jnp
import numpy as np

from paddle_tpu import flags

bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
# fp8 family (the quantized-collective wire + future fp8 matmul work):
# e4m3 carries the payloads — widest mantissa at ±448 range; e5m2 is the
# gradient-friendly wide-range variant kept for parity with phi::DataType
float8_e4m3 = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_NAME2DTYPE = {
    "bfloat16": bfloat16, "float16": float16, "float32": float32,
    "float64": float64, "float8_e4m3": float8_e4m3,
    "float8_e4m3fn": float8_e4m3, "float8_e5m2": float8_e5m2,
    "int8": int8, "int16": int16, "int32": int32,
    "int64": int64, "uint8": uint8, "bool": bool_,
    "complex64": complex64, "complex128": complex128,
}


def to_dtype(d):
    """Normalize a dtype-ish (str, np.dtype, jnp dtype) to a numpy dtype."""
    if isinstance(d, str):
        d = _NAME2DTYPE[d]
    return np.dtype(d)


def set_default_dtype(d) -> None:
    d = to_dtype(d)
    if d not in (np.dtype(np.float32), np.dtype(np.float64),
                 np.dtype(jnp.bfloat16), np.dtype(np.float16)):
        raise ValueError(f"default dtype must be floating, got {d}")
    flags.set_flags({"default_dtype": d.name})


def get_default_dtype():
    return to_dtype(flags.get_flag("default_dtype"))


def is_floating(d) -> bool:
    return jnp.issubdtype(to_dtype(d), jnp.floating)
