"""Legacy reader decorators + paddle.batch (ref: python/paddle/reader/
decorator.py — map_readers, shuffle, buffered, compose, chain, firstn,
cache, xmap_readers; python/paddle/batch.py batch:17).

A "reader" is a zero-arg callable returning an iterable of samples. These
stay host-side generator plumbing (they were in the reference too); the
modern path is io.DataLoader, which the docs point to."""

import random as _random
from itertools import chain as _chain
from queue import Queue
from threading import Thread

__all__ = ["batch", "map_readers", "shuffle", "buffered", "compose",
           "chain", "firstn", "cache", "xmap_readers"]


def batch(reader, batch_size, drop_last=False):
    """ref: python/paddle/batch.py:17 — group samples into lists."""

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def map_readers(func, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


def shuffle(reader, buf_size):
    def reader_():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        _random.shuffle(buf)
        yield from buf
    return reader_


def buffered(reader, size):
    """Prefetch up to ``size`` samples on a thread."""
    end = object()

    def reader_():
        q = Queue(maxsize=size)

        def fill():
            for s in reader():
                q.put(s)
            q.put(end)

        t = Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                return
            yield s
    return reader_


def compose(*readers, check_alignment=True):
    _end = object()

    def reader():
        its = [iter(r()) for r in readers]
        while True:
            items = [next(it, _end) for it in its]
            done = [i is _end for i in items]
            if all(done):
                return
            if any(done):
                # a sentinel (not `is None`) detects the mismatch even
                # when some reader is exactly one element longer or
                # legitimately yields None samples
                if check_alignment:
                    raise ValueError("readers have different lengths")
                return
            out = ()
            for it in items:
                out = out + (it if isinstance(it, tuple) else (it,))
            yield out
    return reader


def chain(*readers):
    def reader():
        return _chain(*[r() for r in readers])
    return reader


def firstn(reader, n):
    def reader_():
        for i, s in enumerate(reader()):
            if i >= n:
                return
            yield s
    return reader_


def cache(reader):
    all_data = None

    def reader_():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        yield from all_data
    return reader_


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool mapped reader (≙ xmap_readers; processes dissolve into
    threads — the work is numpy, the GIL releases in C)."""
    from concurrent.futures import ThreadPoolExecutor

    def reader_():
        with ThreadPoolExecutor(max_workers=process_num) as pool:
            yield from pool.map(mapper, reader())
    return reader_
