"""Unified tracing + metrics pipeline (ISSUE 3 tentpole).

Three legs, one namespace:

- ``trace`` — ring-buffered structured spans with Chrome-trace/Perfetto
  export (``span("p2p/send", bytes=n)``, ``PT_TRACE_DIR``);
- ``stats`` (paddle_tpu.stats) — counters, gauges, timers, and
  log-bucketed histograms (p50/p90/p99) one process-wide registry;
- ``statsz`` — opt-in live HTTP endpoint serving the snapshot
  (``PT_STATSZ_PORT``), scrapeable across a multi-host job.

``init_from_env()`` runs at ``import paddle_tpu`` and activates only
what the env contract asks for — with neither var set, the whole
subsystem stays dormant (one dict lookup per process).
"""

import os

from paddle_tpu.observability import trace
from paddle_tpu.observability.trace import (span, begin, end, complete,
                                            instant)
from paddle_tpu.observability.statsz import (StatszServer, start_statsz,
                                             stop_statsz)
from paddle_tpu.observability.merge import (merge_trace_files,
                                            merge_rank_traces,
                                            stitch_trace_files,
                                            stitch_rank_traces,
                                            request_segments)
from paddle_tpu.observability import comm
from paddle_tpu.observability.comm import (exposed_time, step_overlap,
                                           record_step_overlap)
from paddle_tpu.observability import flight
from paddle_tpu.observability import runtime
from paddle_tpu.observability import devprof
from paddle_tpu.observability import numerics

__all__ = ["trace", "span", "begin", "end", "complete", "instant",
           "StatszServer", "start_statsz", "stop_statsz",
           "merge_trace_files", "merge_rank_traces",
           "stitch_trace_files", "stitch_rank_traces",
           "request_segments", "init_from_env",
           "comm", "exposed_time", "step_overlap", "record_step_overlap",
           "flight", "runtime", "devprof", "numerics"]


def init_from_env():
    """Wire tracing (PT_TRACE_DIR / PT_TRACE_FILE) and the statsz
    server (PT_STATSZ_PORT) from the launch env contract. Idempotent;
    errors never break the importing process (observability must not
    take the job down)."""
    trace._init_from_env()
    port = os.environ.get("PT_STATSZ_PORT")
    if port:
        try:
            start_statsz(int(port))
        except (ValueError, OSError):
            pass  # bad/busy port: the job matters more than the endpoint


init_from_env()
