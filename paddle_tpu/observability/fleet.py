"""Fleet-wide metrics aggregation + SLO/anomaly watch (ISSUE 13
tentpole, router side).

Replicas attach ``stats.export()`` snapshots to their membership
heartbeats (``ReplicaDirectory.heartbeat(stats=...)`` — the load gauges
already ride the same beat); a router-side :class:`FleetStats` keeps
the LATEST export per replica and merges them on demand with PR 3's
merge semantics: counters sum across replicas, histograms merge
bucket-wise exactly (the merged p99 is the p99 of the union of raw
samples to within one 2^¼ bucket), and gauges namespace per replica so
nothing collides. The merged registry serves a fleet-level ``/statsz``
(:meth:`FleetStats.serve_statsz`), appends periodic JSONL telemetry,
and feeds the **SLO/anomaly watch**:

- **SLO burn** — merged ``serve/ttft_s`` p99 against
  ``PT_SLO_TTFT_P99_MS`` (gauge ``fleet/slo_ttft_burn`` = p99/target;
  alert while > 1) and fleet goodput (token progress per second summed
  over replicas, gauge ``fleet/goodput_tokens_per_s``) against
  ``PT_SLO_GOODPUT``.
- **Stalled replica** — heartbeat still alive but ZERO token progress
  for ``stall_after_s`` while the replica shows work (busy slots or a
  non-empty queue). Catches a SIGSTOP/wedged replica long before the
  membership death sweep (whose ``dead_after`` is deliberately
  generous to survive loaded hosts).
- **Runaway queue age** — a replica's oldest queued request older than
  ``PT_SLO_QUEUE_AGE_S``.
- **Pool-page exhaustion** — a paged replica with zero free pages and
  work waiting.

Every detector is EDGE-TRIGGERED: one ``fleet/alert_*`` counter tick
plus one structured log line per incident, cleared when the condition
resolves (so a re-stall alerts again). ``Router.enable_fleet_stats``
pumps :meth:`poll` from the router's own poll loop.
"""

import json
import os
import sys
import threading
import time
from typing import Collection, Dict, List, Optional

from paddle_tpu import stats as stats_lib

__all__ = ["FleetStats", "slo_targets"]


def slo_targets() -> dict:
    """The SLO targets from the env contract (None = unset/disabled):
    ``PT_SLO_TTFT_P99_MS`` (ms), ``PT_SLO_GOODPUT`` (tokens/s floor),
    ``PT_SLO_QUEUE_AGE_S`` (seconds, default 30)."""
    def _f(name, default=None):
        raw = os.environ.get(name)
        if raw is None or raw.strip() == "":
            return default
        try:
            return float(raw)
        except ValueError:
            return default
    return {"ttft_p99_ms": _f("PT_SLO_TTFT_P99_MS"),
            "goodput": _f("PT_SLO_GOODPUT"),
            "queue_age_s": _f("PT_SLO_QUEUE_AGE_S", 30.0)}


class FleetStats:
    """Merge replica stat exports and watch the fleet's health.

        fleet = FleetStats(router.directory)
        fleet.poll()                  # refresh + watch + jsonl
        fleet.merged().snapshot()     # the fleet-level scrape
        fleet.serve_statsz(port)      # live fleet /statsz

    ``directory`` may be None for in-process aggregation via
    :meth:`ingest` (tests, the bench)."""

    def __init__(self, directory=None, dead_after: float = 2.0,
                 stall_after_s: float = 5.0,
                 jsonl_path: Optional[str] = None,
                 jsonl_interval_s: float = 5.0,
                 slo: Optional[dict] = None):
        self.directory = directory
        self.dead_after = float(dead_after)
        self.stall_after_s = float(stall_after_s)
        # the stalled detector must be able to OUTLAST the membership
        # liveness horizon: a SIGSTOP'd replica stops heartbeating too,
        # and with a tight dead_after (Router's default is 2s) it would
        # go "dead" before a longer stall window could ever elapse —
        # the headline alert would be unfireable. Presence for the
        # stall check therefore uses its own horizon covering the full
        # stall window (+margin); the death sweep keeps dead_after.
        self._stall_horizon = max(self.dead_after,
                                  self.stall_after_s + 2.0)
        self.jsonl_path = jsonl_path
        self.jsonl_interval_s = float(jsonl_interval_s)
        self.slo = dict(slo_targets(), **(slo or {}))
        # minimum fresh samples before a TTFT window is judged against
        # the SLO — a 2-sample "window" p99 is noise, not a burn
        self.slo_window_min = 20
        # guards _exports (and _loads) against the fleet /statsz
        # handler threads: merged() runs per scrape on an HTTP thread
        # while the router thread ingests — an unlocked dict would
        # throw mid-iteration the moment a new replica joins
        self._lock = threading.Lock()
        self._exports: Dict[str, dict] = {}   # rid -> latest export
        self._loads: Dict[str, dict] = {}     # rid -> latest load
        self._alive: Dict[str, bool] = {}
        self._present: Dict[str, bool] = {}   # stall-horizon liveness
        self._busy: Dict[str, bool] = {}      # last load's busy state
        # rid -> (last tokens counter, monotonic time it last ADVANCED)
        self._progress: Dict[str, tuple] = {}
        # TTFT SLO window anchor: (merged hist count, merged buckets)
        # at the last judged window — the burn is computed over the
        # DELTA, so a late-onset regression alerts within one window
        # instead of waiting for the lifetime-cumulative p99 to drift,
        # and a recovered fleet re-arms the edge trigger
        self._ttft_window: tuple = (0, {})
        # per-replica goodput anchors: (monotonic t, {rid: tokens}) —
        # per-replica deltas clamp a RESTARTED replica (counter reset)
        # to zero contribution instead of negating the whole fleet's
        self._tokens_window: Optional[tuple] = None
        self._active: set = set()             # edge-trigger state
        self.alerts: List[dict] = []          # every alert ever fired
        self._jsonl_at = 0.0
        self._statsz = None

    # -- ingestion ----------------------------------------------------------

    def ingest(self, rid: str, export: Optional[dict] = None,
               load: Optional[dict] = None, alive: bool = True,
               now: Optional[float] = None,
               present: Optional[bool] = None):
        """Fold one replica's latest snapshot in (the refresh path and
        the in-process test hook). ``export`` REPLACES the replica's
        previous export — exports are cumulative, so keeping only the
        latest makes the merge exact. ``present`` is the stall-horizon
        liveness (defaults to ``alive``; refresh judges it with the
        longer ``_stall_horizon``)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._ingest_locked(rid, export, load, alive, now, present)

    def _ingest_locked(self, rid, export, load, alive, now, present):
        # the lock covers every map signals() snapshots — a controller
        # stepping on its own thread iterates them concurrently with
        # the router-thread ingest
        if export is not None:
            self._exports[rid] = export
        if load is not None:
            self._loads[rid] = load
            busy_now = (load.get("queued", 0) > 0
                        or load.get("busy_slots", 0) > 0)
            toks = load.get("tokens")
            if toks is not None:
                prev = self._progress.get(rid)
                # re-anchor on the idle→busy EDGE too: an idle replica's
                # token counter is legitimately frozen, and judging the
                # first busy beat against that minutes-old anchor would
                # fire a stall alert the instant traffic arrives
                if (prev is None or toks != prev[0]
                        or (busy_now and not self._busy.get(rid))):
                    self._progress[rid] = (toks, now)
            self._busy[rid] = busy_now
        self._alive[rid] = bool(alive)
        self._present[rid] = bool(alive if present is None else present)

    def refresh(self, now: Optional[float] = None):
        """Pull every member's heartbeat-attached export + load gauges
        from the directory (one store read per replica per field)."""
        if self.directory is None:
            return
        for rid in self.directory.members():
            self.ingest(
                rid,
                export=self.directory.stats_export(rid),
                load=self.directory.load(rid),
                alive=self.directory.alive(rid, self.dead_after),
                present=self.directory.alive(rid, self._stall_horizon),
                now=now)
        stats_lib.set_value("fleet/replicas_alive",
                            sum(1 for a in self._alive.values() if a))

    # -- aggregation --------------------------------------------------------

    def merged(self) -> stats_lib.StatRegistry:
        """One registry over the fleet's LATEST exports: counters sum,
        timers/histograms merge bucket-wise, gauges namespace
        ``<rid>/`` (replica ids beat rank numbers here — a fleet of
        nproc=1 launches is all rank 0)."""
        with self._lock:
            exports = dict(self._exports)
        out = stats_lib.StatRegistry()
        for rid in sorted(exports):
            out.load_export(exports[rid], gauge_prefix=f"{rid}/")
        return out

    def export(self) -> dict:
        return self.merged().export(rank=-1)

    def signals(self, role: Optional[str] = None,
                exclude: Collection[str] = ()) -> dict:
        """The fleet controller's condensed input (fleet/controller.py):
        one dict summarizing the PRESENT replicas' heartbeat load
        gauges plus the watch's fleet-level SLO gauges. ``role``
        restricts the view to one serving tier (``prefill`` /
        ``decode`` / ``both``) so a disaggregated fleet's tiers scale
        independently; None aggregates every present replica.
        ``exclude`` drops named rids from the view (the controller
        passes its draining set — those replicas still heartbeat but
        take no new placements, so their slots are not capacity).

        Keys: ``replicas`` (present rids, sorted), ``n_alive``,
        ``queued``, ``busy_slots``/``total_slots``/``occupancy``,
        ``queue_age_s`` (max over replicas), ``free_pages``/
        ``total_pages``, ``ttft_burn`` (fleet/slo_ttft_burn gauge — 0
        until a window is judged), ``goodput`` (fleet/
        goodput_tokens_per_s gauge)."""
        with self._lock:
            loads = {rid: dict(l) for rid, l in self._loads.items()}
            present = dict(self._present)
        rids = sorted(
            rid for rid, l in loads.items()
            if present.get(rid)
            and rid not in exclude
            and (role is None or l.get("role", "both") == role))
        busy = sum(loads[r].get("busy_slots", 0) for r in rids)
        total = sum(loads[r].get("busy_slots", 0)
                    + loads[r].get("free_slots", 0) for r in rids)
        return {
            "replicas": rids,
            "n_alive": len(rids),
            "queued": sum(loads[r].get("queued", 0) for r in rids),
            "busy_slots": busy,
            "total_slots": total,
            "occupancy": (busy / total) if total else 0.0,
            "queue_age_s": max(
                [float(loads[r].get("queue_age_s", 0.0) or 0.0)
                 for r in rids], default=0.0),
            "free_pages": sum(loads[r].get("free_pages", 0)
                              for r in rids),
            "total_pages": sum(loads[r].get("total_pages", 0)
                               for r in rids),
            "ttft_burn": float(stats_lib.get("fleet/slo_ttft_burn", 0.0)),
            "goodput": float(
                stats_lib.get("fleet/goodput_tokens_per_s", 0.0)),
        }

    def serve_statsz(self, port: int = 0, host: str = "0.0.0.0"):
        """Fleet-level /statsz: every scrape serves a freshly merged
        registry. Returns the server (read ``.port``)."""
        from paddle_tpu.observability.statsz import StatszServer
        if self._statsz is None:
            self._statsz = StatszServer(port, host, registry=self.merged)
        return self._statsz

    # -- alerts -------------------------------------------------------------

    def _fire(self, kind: str, key, msg: str) -> bool:
        """Edge-triggered alert: one counter tick + one log line per
        incident; returns True when this call fired it."""
        if key in self._active:
            return False
        self._active.add(key)
        stats_lib.add(f"fleet/alert_{kind}")
        rec = {"t": time.time(), "kind": kind, "msg": msg}
        self.alerts.append(rec)
        print(f"[fleet] ALERT {kind}: {msg}", file=sys.stderr,
              flush=True)
        return True

    def _clear(self, key):
        self._active.discard(key)

    def watch(self, now: Optional[float] = None,
              merged: Optional[stats_lib.StatRegistry] = None
              ) -> List[str]:
        """Run every detector over the current state; returns the alert
        kinds that fired ON THIS CALL (edge transitions only).
        ``merged`` lets :meth:`poll` reuse one merge for watch + jsonl
        instead of rebuilding the full fleet registry per consumer."""
        now = time.monotonic() if now is None else now
        fired: List[str] = []

        # per-replica detectors — PRESENT replicas only: a dead
        # replica's last load is frozen (busy_slots / queue_age /
        # free_pages stuck at whatever it died with) and must neither
        # alert forever nor hold an incident active forever — death is
        # the membership sweep's story, not an anomaly
        for rid, load in self._loads.items():
            if not self._present.get(rid):
                for key in (("stalled", rid), ("queue_age", rid),
                            ("pool", rid)):
                    self._clear(key)
                continue
            busy = (load.get("queued", 0) > 0
                    or load.get("busy_slots", 0) > 0)
            # stalled: recently-heartbeating replica (the stall-horizon
            # presence — see __init__), work on board, tokens frozen
            prog = self._progress.get(rid)
            key = ("stalled", rid)
            if (busy and prog is not None
                    and now - prog[1] > self.stall_after_s):
                if self._fire("stalled_replica", key,
                              f"replica {rid} alive but zero token "
                              f"progress for {now - prog[1]:.1f}s "
                              f"(queued={load.get('queued', 0)}, "
                              f"busy_slots={load.get('busy_slots', 0)})"):
                    fired.append("stalled_replica")
            else:
                self._clear(key)
            # runaway queue age
            age = float(load.get("queue_age_s", 0.0) or 0.0)
            key = ("queue_age", rid)
            limit = self.slo.get("queue_age_s") or 30.0
            if age > limit:
                if self._fire("queue_age", key,
                              f"replica {rid} oldest queued request "
                              f"{age:.1f}s old (limit {limit:.0f}s)"):
                    fired.append("queue_age")
            else:
                self._clear(key)
            # pool-page exhaustion (paged replicas only)
            key = ("pool", rid)
            if (load.get("total_pages", 0) > 0
                    and load.get("free_pages", 0) <= 0
                    and load.get("queued", 0) > 0):
                if self._fire("pool_exhausted", key,
                              f"replica {rid} page pool exhausted with "
                              f"{load.get('queued', 0)} queued"):
                    fired.append("pool_exhausted")
            else:
                self._clear(key)

        # fleet-level SLO burn over a WINDOW of fresh samples: the
        # lifetime-cumulative p99 would both lag a late-onset
        # regression by however much healthy history preceded it AND
        # never recover below target after one incident (permanently
        # disarming the edge trigger). The window is the bucket-wise
        # DELTA of the merged histogram since the last judged window,
        # advanced only once it holds >= slo_window_min samples.
        target = self.slo.get("ttft_p99_ms")
        if target:
            if merged is None:
                merged = self.merged()
            hist = merged.histogram("serve/ttft_s")
            if hist is not None and hist.count:
                prev_n, prev_b = self._ttft_window
                if hist.count < prev_n:
                    # a replica restart REPLACED its cumulative export
                    # with a near-empty one, shrinking the merged
                    # census below the window anchor — re-anchor, or
                    # dn stays negative and the burn gauge/alert is
                    # disarmed until the whole fleet re-serves past
                    # the stale anchor (exactly when a post-restart
                    # regression is likeliest)
                    self._ttft_window = (hist.count,
                                         dict(hist.buckets))
                    prev_n, prev_b = self._ttft_window
                dn = hist.count - prev_n
                if dn >= self.slo_window_min:
                    dh = stats_lib._Histogram()
                    dh.buckets = {
                        i: c - prev_b.get(i, 0)
                        for i, c in hist.buckets.items()
                        if c - prev_b.get(i, 0) > 0}
                    # count from the surviving positive deltas: a
                    # restart landing mid-window can shrink individual
                    # buckets without shrinking the total
                    dh.count = sum(dh.buckets.values())
                    # clamp bounds from the cumulative hist (cosmetic
                    # only — the representative is the bucket midpoint)
                    dh.min, dh.max = hist.min, hist.max
                    if dh.count:
                        p99_ms = dh.percentile(99) * 1e3
                        burn = p99_ms / target
                        stats_lib.set_value("fleet/slo_ttft_burn",
                                            burn)
                        if burn > 1.0:
                            if self._fire(
                                    "slo_ttft", ("slo_ttft",),
                                    f"fleet p99 TTFT {p99_ms:.0f}ms "
                                    f"over the {target:.0f}ms SLO "
                                    f"over the last {dh.count} "
                                    f"requests (burn {burn:.2f})"):
                                fired.append("slo_ttft")
                        else:
                            self._clear(("slo_ttft",))
                    self._ttft_window = (hist.count,
                                         dict(hist.buckets))

        # fleet goodput: PER-REPLICA token deltas over the refresh
        # window (load-gauge counters, so it works even when a wedged
        # replica stops exporting; a restarted replica's reset counter
        # clamps to zero contribution instead of negating the fleet's)
        cur = {rid: int(l.get("tokens", 0))
               for rid, l in self._loads.items()
               if self._present.get(rid)}
        if self._tokens_window is not None:
            t0, prev = self._tokens_window
            dt = now - t0
            if dt > 0.5:
                rate = sum(max(0, c - prev.get(rid, c))
                           for rid, c in cur.items()) / dt
                stats_lib.set_value("fleet/goodput_tokens_per_s", rate)
                floor = self.slo.get("goodput")
                # a dead replica's frozen busy_slots must not keep the
                # fleet "busy" (and the goodput alert armed) forever
                busy = any((l.get("queued", 0) > 0
                            or l.get("busy_slots", 0) > 0)
                           and self._present.get(rid)
                           for rid, l in self._loads.items())
                if floor and busy and rate < floor:
                    if self._fire("slo_goodput", ("slo_goodput",),
                                  f"fleet goodput {rate:.1f} tok/s "
                                  f"under the {floor:.1f} floor"):
                        fired.append("slo_goodput")
                else:
                    self._clear(("slo_goodput",))
                self._tokens_window = (now, cur)
        else:
            self._tokens_window = (now, cur)
        return fired

    # -- telemetry ----------------------------------------------------------

    def append_jsonl(self, path: Optional[str] = None,
                     merged: Optional[stats_lib.StatRegistry] = None):
        """Append one telemetry line: wall time, per-replica load
        gauges, active alerts, and the merged serve/fleet snapshot."""
        path = path or self.jsonl_path
        if not path:
            return None
        if merged is None:
            merged = self.merged()
        snap = merged.snapshot("serve/")
        snap.update(stats_lib.snapshot("fleet/"))
        line = {"t": time.time(),
                "alive": sorted(r for r, a in self._alive.items() if a),
                "loads": self._loads,
                "alerts_active": sorted(str(k) for k in self._active),
                "stats": snap}
        with open(path, "a") as f:
            f.write(json.dumps(line) + "\n")
        return path

    def poll(self, now: Optional[float] = None) -> List[str]:
        """One pump: refresh from the directory, run the watch, append
        JSONL at its own cadence. The router calls this from its poll
        loop (throttling is the caller's business — Router throttles to
        its fleet-stats refresh interval)."""
        self.refresh(now=now)
        # ONE merge per pump, shared by the watch and the telemetry
        # line — merged() deserializes every replica's full export
        merged = self.merged()
        fired = self.watch(now=now, merged=merged)
        t = time.monotonic() if now is None else now
        if self.jsonl_path and t - self._jsonl_at >= self.jsonl_interval_s:
            self._jsonl_at = t
            try:
                self.append_jsonl(merged=merged)
            except OSError:
                pass
        return fired
