"""Live /statsz endpoint: a thread HTTP server scraping tools hit for the
process's current stats snapshot.

Reference analog: the monitor-stat scrape surface (platform/monitor.h
counters dumped by tools) crossed with the *z-page idiom (statusz/varz)
production servers expose. Opt-in: set ``PT_STATSZ_PORT`` or call
``start_statsz()``. Under the launch CLI the launcher holds the base
port and worker rank r serves on ``base + 1 + r`` — a 4-worker node is
scrapeable at base+1..base+4 (launch.py module doc).

Routes:
    /statsz         structured JSON: rank + counters/gauges/timers/
                    histograms (the ``stats.export()`` form — directly
                    feedable to ``stats.merge`` for cross-rank
                    aggregation)
    /statsz?flat=1  flat name→value map (``stats.snapshot()``)
    /metricsz       Prometheus text exposition (version 0.0.4) of the
                    same registry — counters as ``pt_<name>_total``,
                    gauges as ``pt_<name>``, histograms/timers as
                    summaries (p50/p90/p99 quantile samples + _sum/
                    _count) — so fleet replicas scrape with stock
                    tooling (``/metrics`` answers too)
    /               plain-text ``stats.table()`` for humans/curl
"""

import json
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse, parse_qs

__all__ = ["StatszServer", "start_statsz", "stop_statsz",
           "prometheus_text"]

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, suffix: str = "") -> str:
    """``serve/ttft_s`` → ``pt_serve_ttft_s``: slashes/dots become
    underscores, everything lands under one ``pt_`` namespace."""
    return "pt_" + _PROM_BAD.sub("_", name) + suffix


def _prom_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def prometheus_text(registry) -> str:
    """Render a StatRegistry as Prometheus text exposition format
    (0.0.4). Typed from the registry's own taxonomy — counters are
    Prometheus counters (``_total``), gauges gauges, and the
    log-bucketed histograms and timers summaries (quantile samples are
    the registry's p50/p90/p99 estimates; a scraper averages
    *post-scrape* exactly as it would native summary quantiles)."""
    from paddle_tpu import stats as _stats
    exp = registry.export()
    lines = []

    def emit(name, mtype, samples):
        lines.append(f"# TYPE {name} {mtype}")
        for label, v in samples:
            lines.append(f"{name}{label} {_prom_value(v)}")

    for k in sorted(exp.get("counters", {})):
        emit(_prom_name(k, "_total"), "counter",
             [("", exp["counters"][k])])
    for k in sorted(exp.get("gauges", {})):
        emit(_prom_name(k), "gauge", [("", exp["gauges"][k])])
    for k in sorted(exp.get("timers", {})):
        t = exp["timers"][k]
        n = _prom_name(k, "_seconds")
        lines.append(f"# TYPE {n} summary")
        lines.append(f"{n}_sum {_prom_value(t.get('total_s', 0.0))}")
        lines.append(f"{n}_count {_prom_value(t.get('count', 0))}")
    for k in sorted(exp.get("histograms", {})):
        h = _stats._Histogram.from_dict(exp["histograms"][k])
        n = _prom_name(k)
        samples = [(f'{{quantile="{q / 100}"}}', h.percentile(q))
                   for q in (50, 90, 99)]
        lines.append(f"# TYPE {n} summary")
        for label, v in samples:
            lines.append(f"{n}{label} {_prom_value(v)}")
        lines.append(f"{n}_sum {_prom_value(h.sum)}")
        lines.append(f"{n}_count {_prom_value(h.count)}")
    return "\n".join(lines) + "\n"

_server_lock = threading.Lock()
_server: Optional["StatszServer"] = None


class _Handler(BaseHTTPRequestHandler):
    def _registry(self):
        """The registry this server snapshots: the process default, or
        the server's ``registry`` provider (a StatRegistry or a
        callable returning one — the fleet /statsz serves a freshly
        merged registry per scrape this way)."""
        reg = getattr(self.server, "pt_registry", None)
        if reg is None:
            from paddle_tpu import stats
            return stats.default_registry()
        return reg() if callable(reg) else reg

    def do_GET(self):  # noqa: N802 (http.server contract)
        reg = self._registry()
        u = urlparse(self.path)
        if u.path in ("/statsz", "/statsz/"):
            q = parse_qs(u.query)
            if q.get("flat"):
                body = json.dumps(reg.snapshot())
            else:
                body = json.dumps(reg.export())
            ctype = "application/json"
        elif u.path in ("/metricsz", "/metrics"):
            body = prometheus_text(reg)
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif u.path == "/":
            body = reg.table() + "\n"
            ctype = "text/plain; charset=utf-8"
        else:
            self.send_error(404, "try /statsz, /metricsz, or /")
            return
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):  # quiet: scrapes must not spam stderr
        pass


class StatszServer:
    """ThreadingHTTPServer on a daemon thread; ``port=0`` binds an
    ephemeral port (read ``.port`` after construction — tests use
    this). ``registry`` overrides what is served: a StatRegistry, or a
    zero-arg callable returning one evaluated per scrape (the fleet
    /statsz serves ``FleetStats.merged`` through this)."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 registry=None):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.pt_registry = registry
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-statsz",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_statsz(port: int = 0, host: str = "0.0.0.0") -> StatszServer:
    """Start (or return the already-running) statsz server."""
    global _server
    with _server_lock:
        if _server is None:
            _server = StatszServer(port, host)
        return _server


def stop_statsz():
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()
