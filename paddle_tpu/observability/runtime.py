"""Runtime telemetry: live/peak HBM gauges and per-executable memory
footprints (ISSUE 13 tentpole, the on-chip numbers the r06 recapture
needs attributable to a serving timeline).

Two sources, one gauge namespace:

- :func:`hbm_gauges` — the PJRT allocator's live/peak bytes
  (``device.memory_stats()``): ``mem/hbm_bytes_in_use`` and
  ``mem/hbm_peak_bytes`` summed over local devices, plus per-device
  ``mem/hbm_bytes_in_use/d{N}`` when ``per_device=True``. Backends
  without allocator stats (CPU) record nothing and return ``{}`` —
  callers never need to guard.
- :func:`memory_analysis_gauges` — a compiled executable's static
  footprint (``compiled.memory_analysis()``): argument / output /
  temp / generated-code bytes as ``mem/compiled_*_bytes`` gauges,
  labelled per call site via ``name``.

The compile/retrace COUNTERS (``compile/retrace`` and
``compile/retrace/<fn>``) live with the engines' jitted bodies — a
trace-time ``stats.add`` fires exactly once per (re)trace, which is
the dynamic complement to ptlint PT002's static retrace check.
"""

from typing import Optional

__all__ = ["hbm_gauges", "memory_analysis_gauges"]

_MA_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")


def hbm_gauges(devices=None, per_device: bool = False) -> dict:
    """Record the allocator's live/peak HBM bytes as gauges. Returns
    the flat dict recorded (empty when the backend exposes no
    ``memory_stats`` — host CPU)."""
    from paddle_tpu import stats
    if devices is None:
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            return {}
    live = peak = 0
    seen = False
    out = {}
    for i, d in enumerate(devices):
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        seen = True
        b = int(ms.get("bytes_in_use", 0))
        p = int(ms.get("peak_bytes_in_use", ms.get("bytes_in_use", 0)))
        live += b
        peak += p
        if per_device:
            out[f"mem/hbm_bytes_in_use/d{i}"] = b
            out[f"mem/hbm_peak_bytes/d{i}"] = p
    if not seen:
        return {}
    out["mem/hbm_bytes_in_use"] = live
    out["mem/hbm_peak_bytes"] = peak
    for k, v in out.items():
        stats.set_value(k, v)
    return out


def memory_analysis_gauges(compiled, name: Optional[str] = None) -> dict:
    """Record a compiled executable's ``memory_analysis()`` sizes as
    ``mem/compiled_<field>_bytes`` gauges (suffixed ``/<name>`` when
    given). ``compiled`` is the result of ``jit(f).lower(...).compile()``
    (or anything with a ``memory_analysis`` attr). Returns the recorded
    dict; backends without the analysis record nothing."""
    from paddle_tpu import stats
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    sfx = f"/{name}" if name else ""
    for field in _MA_FIELDS:
        v = getattr(ma, field, None)
        if v is None:
            continue
        key = field[:-len("_in_bytes")] if field.endswith("_in_bytes") \
            else field
        out[f"mem/compiled_{key}_bytes{sfx}"] = int(v)
    for k, v in out.items():
        stats.set_value(k, v)
    return out
