"""Merge per-rank Chrome-trace files into ONE Perfetto timeline.

Each rank exports ``trace_rank{N}.json`` with pid = rank (trace.py), so
merging is: concatenate every rank's ``traceEvents``, keep exactly one
``process_name``/``process_sort_index`` metadata pair per rank, and
write a single valid Chrome-trace document — Perfetto shows one lane
per rank, nested host spans inside each. The launcher calls this on
exit when ``PT_TRACE_DIR`` is set; ``tools/trace_merge.py`` is the
offline CLI for log dirs collected from multi-host jobs.
"""

import glob
import json
import os
import re
from typing import List, Optional, Sequence

__all__ = ["merge_trace_files", "merge_rank_traces", "MERGED_NAME"]

MERGED_NAME = "trace_merged.json"
_RANK_RE = re.compile(r"trace_rank(\d+)\.json$")


def _load_events(path: str):
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    if not isinstance(evs, list):
        raise ValueError(f"{path}: no traceEvents array")
    return evs


def merge_trace_files(paths: Sequence[str], out_path: str) -> str:
    """Merge explicit per-rank trace files. A file whose events carry no
    pid (hand-rolled traces) gets its pid inferred from the
    ``trace_rank{N}`` filename, default 0."""
    events = []
    seen_meta = set()
    for path in sorted(paths):
        m = _RANK_RE.search(os.path.basename(path))
        fallback_pid = int(m.group(1)) if m else 0
        for ev in _load_events(path):
            pid = ev.get("pid", fallback_pid)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                key = (pid, ev.get("name"))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
            events.append(ev)
    # guarantee a named lane per rank even for hand-rolled inputs
    for pid in sorted({e["pid"] for e in events}):
        if (pid, "process_name") not in seen_meta:
            events.insert(0, {"name": "process_name", "ph": "M",
                              "pid": pid, "tid": 0,
                              "args": {"name": f"rank{pid}"}})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"merged_from": [os.path.basename(p)
                                         for p in sorted(paths)]}}
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return out_path


def merge_rank_traces(trace_dir: str,
                      out_path: Optional[str] = None) -> Optional[str]:
    """Merge every ``trace_rank*.json`` under ``trace_dir`` into
    ``trace_merged.json`` (or ``out_path``). Returns None when the dir
    holds no rank traces (nothing to merge is not an error — a worker
    may have died before exporting)."""
    paths: List[str] = sorted(
        glob.glob(os.path.join(trace_dir, "trace_rank*.json")))
    if not paths:
        return None
    return merge_trace_files(
        paths, out_path or os.path.join(trace_dir, MERGED_NAME))
