"""Merge per-rank Chrome-trace files into ONE Perfetto timeline —
and STITCH per-replica traces into per-request lanes.

Each rank exports ``trace_rank{N}.json`` with pid = rank (trace.py), so
merging is: concatenate every rank's ``traceEvents``, keep exactly one
``process_name``/``process_sort_index`` metadata pair per rank, and
write a single valid Chrome-trace document — Perfetto shows one lane
per rank, nested host spans inside each. The launcher calls this on
exit when ``PT_TRACE_DIR`` is set; ``tools/trace_merge.py`` is the
offline CLI for log dirs collected from multi-host jobs.

**Stitch mode** (ISSUE 13): serving processes tag request-scoped spans
with the request id (``args.rid`` — minted at router/front-end
admission and carried through mailboxes, handoff meta, and KV blobs),
and every process exports on the SAME wall-clock-rebased timeline
(trace.py's perf→wall offset), so joining per-replica trace files
recovers each request's cross-process story.
:func:`stitch_trace_files` merges the files (one lane per FILE — a
fleet of nproc=1 launches is all rank 0, so filenames, not pids, name
the lanes) and adds a synthetic ``requests`` process with one thread
lane per request showing the phase segments::

    queue-wait → prefill → kv-transfer → decode → stream

derived from span BOUNDARIES (:func:`request_segments`): queue-wait is
client submission (``serve/route`` start) to prefill start
(``serve/admit``), kv-transfer is prefill end to decode start
(``serve/decode`` — covers encode, store transit, routing, fetch,
install), stream is decode end to the router picking up the result.
The segments therefore TILE the client-observed window — their sum
equals the ``serve/route`` span up to clock-rebase error.
"""

import glob
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["merge_trace_files", "merge_rank_traces",
           "stitch_trace_files", "stitch_rank_traces",
           "discover_trace_files", "request_segments", "MERGED_NAME",
           "STITCHED_NAME", "REQUEST_SEGMENTS"]

MERGED_NAME = "trace_merged.json"
STITCHED_NAME = "trace_stitched.json"
REQUEST_SEGMENTS = ("queue-wait", "prefill", "kv-transfer", "decode",
                    "stream")
_RANK_RE = re.compile(r"trace_rank(\d+)\.json$")


def _load_events(path: str):
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    if not isinstance(evs, list):
        raise ValueError(f"{path}: no traceEvents array")
    return evs


def merge_trace_files(paths: Sequence[str], out_path: str) -> str:
    """Merge explicit per-rank trace files. A file whose events carry no
    pid (hand-rolled traces) gets its pid inferred from the
    ``trace_rank{N}`` filename, default 0."""
    events = []
    seen_meta = set()
    for path in sorted(paths):
        m = _RANK_RE.search(os.path.basename(path))
        fallback_pid = int(m.group(1)) if m else 0
        for ev in _load_events(path):
            pid = ev.get("pid", fallback_pid)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                key = (pid, ev.get("name"))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
            events.append(ev)
    # guarantee a named lane per rank even for hand-rolled inputs
    for pid in sorted({e["pid"] for e in events}):
        if (pid, "process_name") not in seen_meta:
            events.insert(0, {"name": "process_name", "ph": "M",
                              "pid": pid, "tid": 0,
                              "args": {"name": f"rank{pid}"}})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"merged_from": [os.path.basename(p)
                                         for p in sorted(paths)]}}
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return out_path


def _rid_spans(events):
    """rid -> [span events], spans sorted by start within each rid."""
    by_rid: Dict[str, list] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        rid = (ev.get("args") or {}).get("rid")
        if rid:
            by_rid.setdefault(str(rid), []).append(ev)
    for evs in by_rid.values():
        evs.sort(key=lambda e: e.get("ts", 0.0))
    return by_rid


def request_segments(events) -> Dict[str, dict]:
    """Derive each request's phase segments (µs timestamps/durations,
    the Chrome-trace unit) from its rid-tagged spans:

    - ``serve/route`` (router: submit → result pickup) anchors the
      client-observed window;
    - the EARLIEST ``serve/admit`` is the prefill phase (redistributed
      re-executions keep their later admits on the raw lanes);
    - the LATEST ``serve/decode`` is the decode phase (the one that
      produced the final result);
    - ``kv-transfer`` is the prefill-end → decode-start boundary gap,
      emitted only when a ``serve/kv_transfer`` (or ``serve/kv_publish``)
      span proves pages actually crossed the wire;
    - ``stream`` is decode end → route end (result transit + pickup).

    Returns ``{rid: {"segments": {name: (ts, dur)}, "client_us": dur
    or None, "pids": [...]}}``. Segments whose boundaries invert under
    cross-host clock skew clamp to zero duration rather than lie."""
    out: Dict[str, dict] = {}
    for rid, evs in sorted(_rid_spans(events).items()):
        def first(name):
            return next((e for e in evs if e["name"] == name), None)

        def last(name):
            hit = None
            for e in evs:
                if e["name"] == name:
                    hit = e
            return hit

        route = first("serve/route")
        admit = first("serve/admit")
        decode = last("serve/decode")
        moved_kv = any(e["name"] in ("serve/kv_transfer",
                                     "serve/kv_publish") for e in evs)
        segs: Dict[str, Tuple[float, float]] = {}
        t0 = route["ts"] if route else None
        if t0 is None:
            q = first("serve/queue")
            t0 = q["ts"] if q else (admit["ts"] if admit else None)
        p_end = None
        if admit is not None:
            if t0 is not None:
                segs["queue-wait"] = (t0, max(0.0, admit["ts"] - t0))
            segs["prefill"] = (admit["ts"], admit.get("dur", 0.0))
            p_end = admit["ts"] + admit.get("dur", 0.0)
        d_end = p_end
        if decode is not None:
            d0 = decode["ts"]
            if moved_kv and p_end is not None:
                segs["kv-transfer"] = (p_end, max(0.0, d0 - p_end))
            segs["decode"] = (d0, decode.get("dur", 0.0))
            d_end = d0 + decode.get("dur", 0.0)
        if route is not None and d_end is not None:
            r_end = route["ts"] + route.get("dur", 0.0)
            segs["stream"] = (d_end, max(0.0, r_end - d_end))
        out[rid] = {"segments": segs,
                    "client_us": route.get("dur") if route else None,
                    "pids": sorted({e.get("pid", 0) for e in evs})}
    return out


def stitch_trace_files(paths: Sequence[str], out_path: str,
                       requests_pid: int = 9999):
    """Join per-replica trace files into ONE Perfetto timeline with a
    per-request lane. Each input FILE becomes one process lane named
    after the file (``trace_pf0.json`` → lane ``pf0``) — replica
    processes launched with nproc_per_node=1 are all rank 0, so the
    exported pids would collide. A synthetic ``requests`` process gets
    one thread per stitched request carrying its phase segments
    (:func:`request_segments`). Returns ``(out_path, summary)`` where
    ``summary`` is the request_segments dict (durations in µs) for
    programmatic assertions (the fleetobs smoke's 10% latency-sum
    check)."""
    events: List[dict] = []
    meta: List[dict] = []
    for i, path in enumerate(sorted(paths)):
        stem = os.path.splitext(os.path.basename(path))[0]
        if stem.startswith("trace_"):
            stem = stem[len("trace_"):]
        pid = 1000 + i
        meta.extend([
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": stem}},
            {"name": "process_sort_index", "ph": "M", "pid": pid,
             "tid": 0, "args": {"sort_index": i + 1}},
        ])
        for ev in _load_events(path):
            if ev.get("ph") == "M":
                continue            # lanes are renamed per file
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
    summary = request_segments(events)
    meta.append({"name": "process_name", "ph": "M", "pid": requests_pid,
                 "tid": 0, "args": {"name": "requests"}})
    meta.append({"name": "process_sort_index", "ph": "M",
                 "pid": requests_pid, "tid": 0,
                 "args": {"sort_index": 0}})
    for idx, (rid, info) in enumerate(summary.items()):
        tid = idx + 1
        meta.append({"name": "thread_name", "ph": "M",
                     "pid": requests_pid, "tid": tid,
                     "args": {"name": rid}})
        for seg, (ts, dur) in info["segments"].items():
            events.append({"name": seg, "ph": "X", "cat": "request",
                           "ts": ts, "dur": dur, "pid": requests_pid,
                           "tid": tid, "args": {"rid": rid}})
    doc = {"traceEvents": meta + events, "displayTimeUnit": "ms",
           "otherData": {
               "stitched_from": [os.path.basename(p)
                                 for p in sorted(paths)],
               "requests": len(summary)}}
    os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)
    return out_path, summary


def merge_rank_traces(trace_dir: str,
                      out_path: Optional[str] = None) -> Optional[str]:
    """Merge every ``trace_rank*.json`` under ``trace_dir`` into
    ``trace_merged.json`` (or ``out_path``). Returns None when the dir
    holds no rank traces (nothing to merge is not an error — a worker
    may have died before exporting)."""
    paths: List[str] = sorted(
        glob.glob(os.path.join(trace_dir, "trace_rank*.json")))
    if not paths:
        return None
    return merge_trace_files(
        paths, out_path or os.path.join(trace_dir, MERGED_NAME))


def discover_trace_files(trace_dir: str) -> List[str]:
    """Every stitchable ``trace_*.json`` under ``trace_dir`` — rank
    files, replica files, the launcher lane — excluding previous
    merge/stitch OUTPUTS (the one discovery rule; the CLI and the
    launcher-exit stitch both use it)."""
    skip = {MERGED_NAME, STITCHED_NAME}
    return [p for p in sorted(
                glob.glob(os.path.join(trace_dir, "trace_*.json")))
            if os.path.basename(p) not in skip]


def stitch_rank_traces(trace_dir: str,
                       out_path: Optional[str] = None) -> Optional[str]:
    """Stitch every ``trace_*.json`` under ``trace_dir`` (rank files,
    replica files, the launcher lane — but not a previous merge/stitch
    output) into ``trace_stitched.json``. Returns None — and leaves no
    file — when no request-tagged spans exist to stitch (a training
    job's trace dir, say): a cheap raw-text probe for the ``"rid"``
    attr key skips the parse + renumber + write entirely for the
    common rid-less case (the launcher exit hook runs this right after
    the plain merge already paid one full load)."""
    paths = discover_trace_files(trace_dir)
    if not paths:
        return None

    def _maybe_rid(path):
        try:
            with open(path) as f:
                return '"rid"' in f.read()
        except OSError:
            return False

    if not any(_maybe_rid(p) for p in paths):
        return None
    out = out_path or os.path.join(trace_dir, STITCHED_NAME)
    out, summary = stitch_trace_files(paths, out)
    if not summary:
        try:
            os.remove(out)
        except OSError:
            pass
        return None
    return out
