"""Process-wide structured tracer: ring-buffered spans with Chrome-trace /
Perfetto export.

Reference analog: the two-generation host/device tracer
(paddle/fluid/platform/profiler/ HostTraceLevel + chrome_tracing.cc
ChromeTracingLogger) — host-side named ranges serialized as the Chrome
``traceEvents`` schema Perfetto loads directly. Device-side timing stays
in the XLA trace (jax.profiler); this tracer covers the host
orchestration: p2p transfers, checkpoint phases, engine scheduling,
train-loop steps.

Design:

- **Lock-cheap ring buffer**: finished spans land in a preallocated
  ring (default 65536 events, ``PT_TRACE_RING`` overrides); recording is
  one short lock around an index bump + slot write. When the ring wraps,
  the oldest events are overwritten and ``trace/dropped`` counts them —
  a tracer must never grow without bound inside a serving loop.
- **Disabled = near-free**: ``span()`` checks one module-level flag and
  returns without touching clocks or locks (the <1% overhead budget on
  the decode benchmark). Enable via ``PT_TRACE_DIR`` env (the atexit
  hook then exports ``trace_rank{N}.json`` there), ``PT_TRACE_FILE``
  (exact path, wins over the dir), or ``enable()``.
- **Nesting**: a thread-local stack gives every span its parent id, so
  request → batch → kernel-dispatch timelines reconstruct in Perfetto.
  Async work that crosses threads uses explicit ``begin()``/``end()``
  tokens; after-the-fact intervals (e.g. a request's full lifetime,
  only known at completion) use ``complete()``.
- **Clocks**: spans time with ``perf_counter_ns`` (monotonic); export
  rebases onto the wall clock via a process-start offset so ranks on
  one host (or NTP-synced hosts) land on a shared timeline.
- **Rank lanes**: exported events use pid = rank (``PT_PROCESS_ID``),
  tid = OS thread id, plus ``process_name`` metadata — the merged
  multi-rank file shows one lane per rank (see
  ``observability.merge``).

In-program collectives (lax.psum et al.) are *traced at issue time*:
the span marks when the host built/dispatched the op, not the on-device
duration — that lives in the XLA trace. Host-side ops (p2p, checkpoint
IO, engine steps) time for real.
"""

import functools
import json
import os
import threading
import time
from typing import Optional

__all__ = ["span", "begin", "end", "complete", "instant", "enable",
           "disable", "enabled", "export", "events", "clear",
           "trace_file_from_env", "start_flush"]

_DEFAULT_RING = 65536

# perf_counter epoch → wall-clock epoch, fixed at import: every rank
# exports timestamps on the shared wall timeline
_WALL_OFFSET_NS = time.time_ns() - time.perf_counter_ns()


def _rank() -> int:
    try:
        return int(os.environ.get("PT_PROCESS_ID", 0))
    except ValueError:
        return 0


class _Tracer:
    """The process-wide recorder. One instance; tests may swap capacity
    via clear(capacity=...)."""

    def __init__(self, capacity: int = _DEFAULT_RING):
        self.enabled = False
        self.capacity = int(capacity)
        self._ring = [None] * self.capacity
        self._n = 0                      # monotonic event count
        self._next_id = 0
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.out_path: Optional[str] = None
        self._dropped_reported = 0

    # -- ids / stacks -------------------------------------------------------
    def new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- recording ----------------------------------------------------------
    def record(self, name, t0_ns, dur_ns, sid, parent, attrs):
        ev = (name, t0_ns, dur_ns, threading.get_native_id(), sid,
              parent, attrs)
        with self._lock:
            self._ring[self._n % self.capacity] = ev
            self._n += 1

    def events(self):
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                out = [e for e in self._ring[:n]]
            else:
                i = n % cap
                out = self._ring[i:] + self._ring[:i]
            return out, max(0, n - cap)

    def clear(self, capacity: Optional[int] = None):
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
            self._ring = [None] * self.capacity
            self._n = 0
            self._dropped_reported = 0


_TRACER = _Tracer()


class _Span:
    """Context manager + decorator for one named range. Mutate ``attrs``
    inside the ``with`` block to attach values only known mid-span
    (payload bytes, token counts)."""

    __slots__ = ("name", "attrs", "_t0", "_sid", "_parent", "_live")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs
        self._live = False

    def __enter__(self):
        tr = _TRACER
        if not tr.enabled:
            return self
        self._live = True
        self._sid = tr.new_id()
        st = tr.stack()
        self._parent = st[-1] if st else 0
        st.append(self._sid)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if not self._live:
            return False
        t1 = time.perf_counter_ns()
        tr = _TRACER
        st = tr.stack()
        if st and st[-1] == self._sid:
            st.pop()
        tr.record(self.name, self._t0, t1 - self._t0, self._sid,
                  self._parent, self.attrs or None)
        self._live = False
        return False

    def __call__(self, fn):
        name, attrs = self.name, self.attrs

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with _Span(name, dict(attrs) if attrs else {}):
                return fn(*a, **kw)

        return wrapper


def span(name: str, **attrs) -> _Span:
    """``with span("p2p/send", dst=3) as sp: ... sp.attrs["bytes"] = n``
    — or ``@span("ckpt/save")`` as a decorator. Disabled tracing makes
    __enter__/__exit__ no-ops (one flag check)."""
    return _Span(name, attrs)


def begin(name: str, **attrs):
    """Explicit async begin: returns a token for ``end()``. The span is
    parentless unless ``parent=`` (a token/sid) is passed in attrs —
    async work crosses threads, so the thread-local stack is not used."""
    tr = _TRACER
    if not tr.enabled:
        return None
    parent = attrs.pop("parent", None)
    return (name, time.perf_counter_ns(), tr.new_id(),
            parent[2] if isinstance(parent, tuple) else (parent or 0),
            attrs)


def end(token, **extra_attrs):
    """Close a ``begin()`` token (no-op for None tokens)."""
    tr = _TRACER
    if token is None or not tr.enabled:
        return
    name, t0, sid, parent, attrs = token
    if extra_attrs:
        attrs = {**attrs, **extra_attrs}
    tr.record(name, t0, time.perf_counter_ns() - t0, sid, parent,
              attrs or None)


def complete(name: str, t0_s: float, t1_s: Optional[float] = None,
             **attrs):
    """Record an interval after the fact from ``time.perf_counter()``
    endpoints (seconds) — e.g. a serving request's submit→done lifetime,
    only known at completion."""
    tr = _TRACER
    if not tr.enabled:
        return
    t1_s = time.perf_counter() if t1_s is None else t1_s
    tr.record(name, int(t0_s * 1e9), int((t1_s - t0_s) * 1e9),
              tr.new_id(), 0, attrs or None)


def instant(name: str, **attrs):
    """Zero-duration marker event."""
    tr = _TRACER
    if not tr.enabled:
        return
    tr.record(name, time.perf_counter_ns(), 0, tr.new_id(), 0,
              attrs or None)


# -- lifecycle ---------------------------------------------------------------

def enable(out_path: Optional[str] = None,
           capacity: Optional[int] = None):
    """Turn recording on. ``out_path``: where the atexit/``export()``
    default write goes (a .json file path, or a directory that gets
    ``trace_rank{N}.json``)."""
    if capacity is not None:
        _TRACER.clear(capacity)
    if out_path is not None:
        _TRACER.out_path = out_path
    _TRACER.enabled = True


def disable():
    _TRACER.enabled = False


def enabled() -> bool:
    return _TRACER.enabled


def clear(capacity: Optional[int] = None):
    _TRACER.clear(capacity)


def events():
    """(recorded event tuples oldest→newest, dropped count)."""
    return _TRACER.events()


def trace_file_from_env() -> Optional[str]:
    """Resolve the per-rank output path from the env contract:
    PT_TRACE_FILE (exact, set per worker by the launcher) beats
    PT_TRACE_DIR/trace_rank{N}.json."""
    f = os.environ.get("PT_TRACE_FILE")
    if f:
        return f
    d = os.environ.get("PT_TRACE_DIR")
    if d:
        return os.path.join(d, f"trace_rank{_rank()}.json")
    return None


_EXPORT_LOCK = threading.Lock()


def export(path: Optional[str] = None) -> Optional[str]:
    """Write the ring as Chrome-trace JSON (``{"traceEvents": [...]}``)
    that loads in Perfetto / chrome://tracing. Returns the path written
    (None when there is nowhere to write). pid = rank, tid = OS thread;
    span/parent ids ride in ``args`` so tooling can rebuild the tree.
    Serialized by a module lock: the periodic flush thread and the
    atexit/explicit export would otherwise truncate each other's
    ``.tmp`` mid-write and rename interleaved bytes into the published
    file — the atomic-rewrite guarantee holds only with one writer."""
    path = path or _TRACER.out_path or trace_file_from_env()
    if path is None:
        return None
    if os.path.isdir(path):
        path = os.path.join(path, f"trace_rank{_rank()}.json")
    evs, dropped = _TRACER.events()
    rank = _rank()
    out = [{
        "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
        "args": {"name": f"rank{rank}"},
    }, {
        "name": "process_sort_index", "ph": "M", "pid": rank, "tid": 0,
        "args": {"sort_index": rank},
    }]
    for name, t0, dur, tid, sid, parent, attrs in evs:
        args = {"span_id": sid, "parent_id": parent}
        if attrs:
            args.update(attrs)
        out.append({
            "name": name, "ph": "X", "cat": "host",
            "ts": (t0 + _WALL_OFFSET_NS) / 1e3,       # microseconds
            "dur": dur / 1e3,
            "pid": rank, "tid": tid, "args": args,
        })
    if dropped > _TRACER._dropped_reported:
        from paddle_tpu import stats
        stats.add("trace/dropped", dropped - _TRACER._dropped_reported)
        _TRACER._dropped_reported = dropped
    doc = {"traceEvents": out, "displayTimeUnit": "ms",
           "otherData": {"rank": rank, "dropped": dropped}}
    with _EXPORT_LOCK:
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    return path


_FLUSH_THREAD = None


def _flush_interval_from_env() -> float:
    """Unset / empty / malformed all mean the documented DEFAULT (5s)
    — only an explicit '0' (or negative) disables the flush. An empty
    template variable must not silently switch off the hard-kill
    trace-loss fix this knob exists for."""
    raw = os.environ.get("PT_TRACE_FLUSH_S")
    if raw is None or raw.strip() == "":
        return 5.0
    try:
        return float(raw)
    except ValueError:
        return 5.0


def start_flush(interval_s: Optional[float] = None):
    """Periodic atomic rewrite of the (partial) trace file — the
    trace-loss-on-hard-kill fix: the ring otherwise exports only via
    atexit, so a SIGKILLed replica (exactly the interesting one) left
    no trace at all. Every ``interval_s`` seconds (default
    ``PT_TRACE_FLUSH_S``, 5s; <= 0 disables) the ring is exported via
    the tmp-file + rename path, so readers always see a complete JSON
    document and a hard kill loses at most one interval of spans.
    Idempotent; the thread is a daemon and re-checks ``enabled`` every
    tick, so ``disable()`` quiesces it."""
    global _FLUSH_THREAD
    iv = _flush_interval_from_env() if interval_s is None \
        else float(interval_s)
    if iv <= 0 or _FLUSH_THREAD is not None:
        return None

    def _loop():
        while True:
            time.sleep(iv)
            if not _TRACER.enabled:
                continue
            try:
                export()
            except Exception:
                pass

    t = threading.Thread(target=_loop, name="pt-trace-flush",
                         daemon=True)
    t.start()
    _FLUSH_THREAD = t
    return t


def _init_from_env():
    """PT_TRACE_DIR / PT_TRACE_FILE switch tracing on for this process;
    the atexit hook exports what the ring holds and the periodic flush
    (PT_TRACE_FLUSH_S) keeps a partial export on disk between
    harvests. The output path is NOT latched here: PT_PROCESS_ID may
    only be published after import (env.init_parallel_env with an
    explicit process_id), so export() re-resolves trace_file_from_env()
    at write time — every rank lands on its own trace_rank{N}.json."""
    if trace_file_from_env() is None:
        return
    try:
        capacity = int(os.environ.get("PT_TRACE_RING", _DEFAULT_RING))
    except ValueError:
        capacity = _DEFAULT_RING
    enable(capacity=capacity)
    start_flush()
    import atexit

    def _dump():
        try:
            export()
        except Exception:
            pass

    atexit.register(_dump)
