"""Device-time attribution (ISSUE 15 tentpole): where do the
nanoseconds go on-device?

The fleet plane (PR 13) answers *where a request goes*; this module
answers what the chip did with the time once the request got there.
Three meters, one ``prof/`` namespace:

- **Roofline capture** — :func:`capture_jit` AOT-lowers a jitted fn and
  pulls XLA's ``cost_analysis()`` (FLOPs, HBM bytes moved) plus
  ``memory_analysis()`` for THE program that runs (not a paper model of
  it). :func:`roofline_tokens_per_sec` combines the capture with the
  device peak specs (detected from the attached device, overridable via
  ``PT_PROF_PEAK_FLOPS`` / ``PT_PROF_PEAK_HBM_GBPS``) into an analytic
  tok/s bound, and :func:`record_roofline` turns a measured number into
  the ``prof/roofline_frac`` gauge. Both engines expose
  ``dispatch_cost()`` which captures their decode-dispatch jit at the
  current geometry.
- **Launch-tax meter** — :func:`launch_tax_s` calibrates the
  per-dispatch overhead once per process by timing a no-op jitted
  launch end to end (enqueue + tiny device→host readback: the exact
  shape of the engines' dispatch+harvest round). Multiplied by the
  PR 13 ``serve/dispatch_launches`` counters
  (:func:`launch_tax_fraction`), it prices the "one-pallas-launch-per-
  layer at short lengths" hypothesis (PAPERS: "LLM Inference
  Acceleration via Efficient Operation Fusion") as a printed fraction
  of token time instead of a suspicion. The number is an upper bound
  under pipelining (in-flight dispatches overlap their launch costs).
- **Step decomposition** — :func:`step_fractions` splits a serve/train
  window into device-busy / host-gap / dispatch-queue fractions using
  ``observability/comm.py``'s exact interval algebra over the already-
  recorded trace spans (``serve/dispatch`` = host enqueueing device
  work, ``serve/harvest`` = host blocked on device output; anything
  else is host gap). ``prof/host_bound`` flags a pipeline whose host
  gap exceeds the device-interaction time.

Everything records into the stats registry under ``prof/`` (catalogued
in docs/observability.md) so /statsz, /metricsz, and bench provenance
all see the same numbers.
"""

import os
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from paddle_tpu.observability import comm

__all__ = ["CostCapture", "capture_jit", "peak_specs", "hbm_seconds",
           "roofline_tokens_per_sec", "record_roofline",
           "launch_tax_s", "pallas_launch_tax_s", "launch_tax_fraction",
           "step_fractions", "count_pallas_launches",
           "count_hlo_custom_calls"]


# ---------------------------------------------------------------------------
# device peak specs
# ---------------------------------------------------------------------------

def peak_specs(device=None) -> Tuple[float, float]:
    """``(peak_flops_per_s, peak_hbm_bytes_per_s)`` for ``device``
    (default: the first local device), from the cost model's public
    per-generation table. ``PT_PROF_PEAK_FLOPS`` (FLOP/s) and
    ``PT_PROF_PEAK_HBM_GBPS`` (GB/s) override detection — the knob for
    chips the table predates or deliberately derated rooflines."""
    env_f = os.environ.get("PT_PROF_PEAK_FLOPS")
    env_b = os.environ.get("PT_PROF_PEAK_HBM_GBPS")
    flops = bw = None
    if env_f:
        flops = float(env_f)
    if env_b:
        bw = float(env_b) * 1e9
    if flops is None or bw is None:
        from paddle_tpu.cost_model import _peak
        if device is None:
            import jax
            device = jax.devices()[0]
        det_f, det_b, _ = _peak(device)
        flops = det_f if flops is None else flops
        bw = det_b if bw is None else bw
    return flops, bw


def hbm_seconds(nbytes: float, device=None) -> float:
    """Analytic seconds to move ``nbytes`` through HBM at the device's
    peak bandwidth — the roofline price tag ptgeom's PT009 attaches to
    redundant refetch traffic. Raises when no device/override is
    available (callers guard; static analysis must stay device-free)."""
    _, bw = peak_specs(device)
    return float(nbytes) / bw


# ---------------------------------------------------------------------------
# roofline capture
# ---------------------------------------------------------------------------

@dataclass
class CostCapture:
    """One AOT-lowered program's cost profile: FLOPs and HBM bytes per
    call (XLA cost_analysis) plus the static memory footprint
    (memory_analysis, ``mem/compiled_*`` fields)."""
    name: str
    flops: float
    hbm_bytes: float
    memory: Dict[str, int] = field(default_factory=dict)

    def analytic_seconds(self, peaks: Tuple[float, float]) -> float:
        """Roofline seconds per call: max(compute time, HBM time)."""
        pf, pb = peaks
        return max(self.flops / pf, self.hbm_bytes / pb)


def capture_jit(jfn, *args, name: Optional[str] = None,
                record: bool = True, **kwargs) -> CostCapture:
    """AOT-lower ``jfn`` (a ``jax.jit`` callable) on ``args`` and pull
    its cost/memory analysis. Never executes the program — donated
    buffers stay live. Records ``prof/flops[/name]`` and
    ``prof/hbm_bytes[/name]`` gauges plus the ``mem/compiled_*``
    footprint (runtime.memory_analysis_gauges) unless ``record=False``.
    Compilation rides the jit/persistent cache, so a warmed engine pays
    only the (re)trace."""
    compiled = jfn.lower(*args, **kwargs).compile()
    data = compiled.cost_analysis()
    if isinstance(data, (list, tuple)):   # older jax: list of dicts
        data = data[0] if data else {}
    if not isinstance(data, dict):
        data = {}
    cap = CostCapture(name=name or getattr(jfn, "__name__", "jit"),
                      flops=float(data.get("flops", 0.0)),
                      hbm_bytes=float(data.get("bytes accessed", 0.0)))
    if record:
        from paddle_tpu import stats
        from paddle_tpu.observability import runtime
        sfx = f"/{name}" if name else ""
        stats.set_value(f"prof/flops{sfx}", cap.flops)
        stats.set_value(f"prof/hbm_bytes{sfx}", cap.hbm_bytes)
        cap.memory = runtime.memory_analysis_gauges(compiled, name)
    else:
        try:
            ma = compiled.memory_analysis()
            cap.memory = {"temp_size_in_bytes":
                          int(getattr(ma, "temp_size_in_bytes", 0))}
        except Exception:
            pass
    return cap


def count_pallas_launches(fn, *args, **kwargs) -> int:
    """Kernel launches per CALL of ``fn``, from its jaxpr: every
    ``pallas_call`` equation counts once (a multi-step grid is still
    ONE launch), weighted by the trip count of enclosing ``scan``s —
    so a chunked decode dispatch reports chunk × launches-per-step.
    Backend-independent (interpret-mode pallas_calls count the same),
    which is what lets the CPU suite assert the single-dispatch
    contract the ISSUE 19 megakernel exists for. ``while`` bodies
    count once (trip count unknown — a lower bound); ``cond`` branches
    count at the worst case."""
    import jax

    def walk(jaxpr, mult):
        n = 0
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "pallas_call":
                n += mult
            elif prim == "scan":
                n += walk(eqn.params["jaxpr"].jaxpr,
                          mult * int(eqn.params["length"]))
            elif prim == "while":
                n += walk(eqn.params["cond_jaxpr"].jaxpr, mult)
                n += walk(eqn.params["body_jaxpr"].jaxpr, mult)
            elif prim == "cond":
                n += max((walk(b.jaxpr, mult)
                          for b in eqn.params["branches"]), default=0)
            else:
                for key in ("jaxpr", "call_jaxpr"):
                    sub = eqn.params.get(key)
                    if sub is not None:
                        n += walk(getattr(sub, "jaxpr", sub), mult)
        return n

    return walk(jax.make_jaxpr(fn)(*args, **kwargs).jaxpr, 1)


def count_hlo_custom_calls(jfn, *args, **kwargs) -> Optional[int]:
    """Custom-call count from the AOT-COMPILED HLO of ``jfn`` (a
    ``jax.jit`` callable) — on TPU every pallas kernel lowers to one
    ``tpu_custom_call``, so this is launches-per-call as the runtime
    sees them. Interpret-mode pallas (CPU) lowers to plain HLO, so the
    count reads 0 there — pair with `count_pallas_launches` for a
    backend-independent number. None when lowering fails."""
    try:
        txt = jfn.lower(*args, **kwargs).compile().as_text()
    except Exception:
        return None
    return txt.count("custom-call")


def roofline_tokens_per_sec(cap: CostCapture, tokens_per_call: float,
                            device=None,
                            peaks: Optional[Tuple[float, float]] = None
                            ) -> float:
    """Analytic roofline tok/s for a captured dispatch emitting
    ``tokens_per_call`` tokens: tokens / max(flops/peak_flops,
    bytes/peak_bw). Returns 0.0 when the capture carries no cost data
    (a backend without cost_analysis) — callers treat 0 as "no
    roofline", never as a target."""
    if peaks is None:
        peaks = peak_specs(device)
    t = cap.analytic_seconds(peaks)
    if t <= 0.0 or tokens_per_call <= 0:
        return 0.0
    return tokens_per_call / t


def record_roofline(name: str, measured_tps: float,
                    analytic_tps: float) -> float:
    """Record ``prof/roofline_tps[/name]`` and ``prof/roofline_frac
    [/name]`` (measured/analytic; 0 when no analytic bound exists) and
    return the fraction."""
    from paddle_tpu import stats
    frac = measured_tps / analytic_tps if analytic_tps > 0 else 0.0
    sfx = f"/{name}" if name else ""
    stats.set_value(f"prof/roofline_tps{sfx}", analytic_tps)
    stats.set_value(f"prof/roofline_frac{sfx}", frac)
    return frac


# ---------------------------------------------------------------------------
# launch-tax meter
# ---------------------------------------------------------------------------

_launch_cache: Dict[str, float] = {}


def _median(xs):
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def launch_tax_s(force: bool = False) -> float:
    """Per-dispatch overhead of one no-op jitted launch, calibrated
    ONCE per process (``force=True`` recalibrates): median wall time of
    enqueue + scalar readback on an 8-element array — the same
    host↔device round the engines pay per dispatch+harvest, with zero
    device work inside. Iteration count via ``PT_PROF_LAUNCH_ITERS``
    (default 64; the median is robust to GC/scheduler outliers).
    Records the ``prof/launch_tax_s`` gauge."""
    if not force and "jit" in _launch_cache:
        return _launch_cache["jit"]
    import jax
    import jax.numpy as jnp
    iters = int(os.environ.get("PT_PROF_LAUNCH_ITERS", "64"))
    f = jax.jit(lambda v: v + 1)
    x = jnp.zeros((8,), jnp.int32)
    x = f(x)
    # sync by scalar fetch: on the tunneled PJRT backend
    # block_until_ready does not block (profile_decode.py r5 notes)
    int(x[0])  # ptlint: disable=PT001 -- calibration IS the timed sync
    samples = []
    for _ in range(max(8, iters)):
        t0 = time.perf_counter()
        y = f(x)
        int(y[0])  # ptlint: disable=PT001 -- calibration IS the timed sync
        samples.append(time.perf_counter() - t0)
    tax = _median(samples)
    _launch_cache["jit"] = tax
    from paddle_tpu import stats
    stats.set_value("prof/launch_tax_s", tax)
    return tax


def pallas_launch_tax_s(force: bool = False) -> Optional[float]:
    """Per-dispatch overhead of one no-op Pallas kernel launch —
    the per-layer cost the fused paged path pays at short lengths.
    TPU-only: returns None elsewhere (interpret-mode Pallas on CPU
    times the interpreter, not a launch). Cached per process; records
    ``prof/launch_tax_pallas_s`` when measurable."""
    if not force and "pallas" in _launch_cache:
        return _launch_cache["pallas"]
    try:
        import jax
        if jax.default_backend() != "tpu":
            return None
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _noop(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        f = jax.jit(lambda v: pl.pallas_call(
            _noop, out_shape=jax.ShapeDtypeStruct(v.shape, v.dtype))(v))
        x = jnp.zeros((8, 128), jnp.float32)
        x = f(x)
        float(x[0, 0])  # ptlint: disable=PT001 -- calibration sync
        iters = int(os.environ.get("PT_PROF_LAUNCH_ITERS", "64"))
        samples = []
        for _ in range(max(8, iters)):
            t0 = time.perf_counter()
            y = f(x)
            float(y[0, 0])  # ptlint: disable=PT001 -- calibration sync
            samples.append(time.perf_counter() - t0)
        tax = _median(samples)
    except Exception:
        return None
    _launch_cache["pallas"] = tax
    from paddle_tpu import stats
    stats.set_value("prof/launch_tax_pallas_s", tax)
    return tax


def launch_tax_fraction(dispatches: int, wall_s: float,
                        tax_s: Optional[float] = None,
                        name: Optional[str] = None) -> float:
    """Fraction of ``wall_s`` spent on per-dispatch launch overhead:
    ``dispatches * tax / wall``, clamped to [0, 1] (pipelined launches
    overlap, so the product is an upper bound). ``dispatches`` is the
    PR 13 ``serve/dispatch_launches`` delta over the window. Records
    ``prof/launch_tax_frac[/name]``."""
    if tax_s is None:
        tax_s = launch_tax_s()
    frac = 0.0 if wall_s <= 0 else min(1.0, dispatches * tax_s / wall_s)
    from paddle_tpu import stats
    sfx = f"/{name}" if name else ""
    stats.set_value(f"prof/launch_tax_frac{sfx}", frac)
    return frac


# ---------------------------------------------------------------------------
# step decomposition
# ---------------------------------------------------------------------------

def step_fractions(events=None,
                   window: Optional[Tuple[float, float]] = None,
                   dispatch_prefix: str = "serve/dispatch",
                   harvest_prefix: str = "serve/harvest",
                   host_bound_threshold: float = 0.5,
                   record: bool = True) -> Dict[str, float]:
    """Split a serving window into device-interaction vs host-gap
    fractions from the trace ring, with comm.py's exact interval
    algebra doing the union/subtraction:

    - ``device_frac`` — union(dispatch ∪ harvest spans) / wall: the
      host is feeding the device or blocked on its output.
    - ``queue_frac`` — union(harvest spans) / wall: blocked draining
      the dispatch queue (the device-bound signature — ⊂ device_frac).
    - ``host_frac`` — 1 − device_frac: pure host work (scheduling,
      detokenize, python) the device idles through at depth 1.
    - ``host_bound`` — 1.0 when host_frac > ``host_bound_threshold``.

    ``window`` defaults to the extent of the matched spans. Returns {}
    when nothing matched (no tracing, or an empty window). Pass
    ``dispatch_prefix="compute/"`` / ``harvest_prefix="collective/"``
    to decompose a train window with the same algebra. Records the
    ``prof/device_frac`` / ``prof/queue_frac`` / ``prof/host_frac`` /
    ``prof/host_bound`` gauges."""
    if events is None:
        from paddle_tpu.observability import trace
        events, _ = trace.events()
    disp = comm.span_intervals(events, dispatch_prefix, window)
    harv = comm.span_intervals(events, harvest_prefix, window)
    both = disp + harv
    if not both:
        return {}
    if window is None:
        window = (min(a for a, _ in both), max(b for _, b in both))
    wall = window[1] - window[0]
    if wall <= 0:
        return {}
    # exposed_time([window], spans) = window time covered by NO span —
    # the same union/intersection machinery comm/exposed_s runs on
    host_gap = comm.exposed_time([window], both)
    queue_busy = wall - comm.exposed_time([window], harv)
    out = {
        "wall_s": wall,
        "device_frac": (wall - host_gap) / wall,
        "queue_frac": queue_busy / wall,
        "host_frac": host_gap / wall,
    }
    out["host_bound"] = 1.0 if out["host_frac"] > host_bound_threshold \
        else 0.0
    if record:
        from paddle_tpu import stats
        for k in ("device_frac", "queue_frac", "host_frac",
                  "host_bound"):
            stats.set_value(f"prof/{k}", out[k])
    return out
