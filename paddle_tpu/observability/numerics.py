"""Training-numerics observability plane (ISSUE 18).

The repo quantizes nearly every wire byte (int8/fp8 gradient
reduce-scatters, bucketed overlap collectives, lossy KV pages) on the
strength of fixed-seed parity tests; this module turns those one-shot
claims into *continuously measured* gauges and gives non-finite
failures a provenance better than "the loss is NaN":

- **in-graph capture**: cheap per-leaf / per-layer summaries (rms,
  amax, non-finite count, dtype overflow/underflow fraction) computed
  INSIDE the jitted train step and concatenated into ONE small f32
  device vector, so a sampled step costs the host exactly one packed
  transfer — the same packed-harvest invariant the serving engines
  live by and ptlint PT001 machine-checks.
- **NaN provenance**: a layer-major argmax reduction over the
  per-layer non-finite counts, captured in the same vector — the host
  learns *first bad layer + leaf family*, not just "something broke".
- **cadence**: ``PT_NUMERICS_EVERY`` (0=off). At 1 every step is
  sampled; at k>1 the whole stats subgraph sits behind a
  ``lax.cond`` on the optimizer step counter, so off-cadence steps
  skip both the device compute and the host transfer.
- **host plane**: :class:`Monitor` unpacks the vector, records ``num/``
  gauges into the stats registry (→ /statsz + /metricsz for free),
  feeds :class:`NumericsWatch` (edge-triggered detectors à la
  FleetStats) and a bounded :class:`NumericsRecorder` ring that
  auto-dumps its last-N snapshots (flight-recorder idiom,
  pid-suffixed) when a detector fires.

Bit-parity contract: capture only *reads* values after they exit the
pinned (``optimization_barrier``) subgraphs of the overlap/quantized
step builders — it never feeds anything back into the update math, so
enabling numerics cannot move a single bit of the parameters.
"""

import json
import math
import os
import sys
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu import stats as stats_lib

__all__ = [
    "COLS", "QCOLS", "FAULT_SITE",
    "every", "enabled", "ring_capacity",
    "leaf_raw", "stacked_raw", "pooled_raw", "quant_raw",
    "Packer", "Layout", "LayoutBox", "cond_every", "capture_step",
    "add_grad_tree", "grad_families",
    "poison_grads", "poison_layer_slice",
    "Monitor", "NumericsWatch", "NumericsRecorder", "split_out",
]

# raw per-layer columns carried on device; everything host-facing
# (rms, fractions) derives from these so cross-layer/cross-rank
# reductions stay exact sums/maxes
COLS = ("sumsq", "amax", "nonfinite", "overflow", "underflow")
NCOL = len(COLS)
# raw per-bucket quantization columns: residual/orig/grad sum-squares
QCOLS = ("err_ss", "orig_ss", "grad_ss")
NQCOL = len(QCOLS)
# packed-vector header: [tag, loss, nonfinite_total, first_bad_layer,
# first_bad_family]; tag==1.0 marks a computed (on-cadence) sample —
# the lax.cond zero branch leaves it 0.0 so the host can tell
HEADER = ("tag", "loss", "nonfinite", "first_bad_layer",
          "first_bad_family")
NHDR = len(HEADER)

FAULT_SITE = "train.grad_poison"


# -- knobs (declared in flags.py; PT005) -------------------------------------

def every() -> int:
    """PT_NUMERICS_EVERY: sample every k-th step; 0 disables capture
    entirely (the step builders emit their unchanged 3-tuple)."""
    try:
        return max(0, int(os.environ.get("PT_NUMERICS_EVERY", "0") or 0))
    except ValueError:
        return 0


def enabled() -> bool:
    return every() > 0


def ring_capacity() -> int:
    try:
        return max(1, int(os.environ.get("PT_NUMERICS_RING", "64")))
    except ValueError:
        return 64


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _dump_dir() -> Optional[str]:
    return (os.environ.get("PT_NUMERICS_DIR")
            or os.environ.get("PT_FLIGHT_DIR")
            or os.environ.get("PT_TRACE_DIR"))


# -- in-graph raw summaries ---------------------------------------------------

def _limits(dtype) -> Tuple[float, float]:
    """(overflow threshold, underflow threshold) for a float dtype —
    |x| beyond 90% of finfo.max counts as overflow-at-risk, nonzero
    |x| under finfo.tiny counts as underflow (subnormal)."""
    try:
        fi = jnp.finfo(dtype)
        # ptlint: disable=PT001 -- finfo bounds are static dtype metadata
        return 0.9 * float(fi.max), float(fi.tiny)
    except ValueError:          # integer leaf — no float range to watch
        return float("inf"), 0.0


def leaf_raw(x) -> jnp.ndarray:
    """(NCOL,) raw summary of one whole tensor."""
    return stacked_raw(jnp.reshape(x, (1, -1)))[0]


def stacked_raw(x) -> jnp.ndarray:
    """(L, NCOL) raw summary of a stacked leaf with leading layer dim —
    the PR 8 scan-over-layers axis — reducing over all other dims."""
    hi, lo = _limits(x.dtype)
    xf = jnp.asarray(x).astype(jnp.float32)
    axes = tuple(range(1, xf.ndim))
    fin = jnp.isfinite(xf)
    ax = jnp.where(fin, jnp.abs(xf), 0.0)
    # nonzero-magnitude test on the BITS: XLA CPU flushes subnormals
    # in float compares (1e-40 > 0 is False there), which would hide
    # exactly the values the underflow column exists to count
    nz = (lax.bitcast_convert_type(xf, jnp.uint32) << 1) != 0
    one = jnp.float32(1.0)
    return jnp.stack([
        jnp.sum(jnp.where(fin, xf * xf, 0.0), axis=axes),
        jnp.max(ax, axis=axes) if axes else ax,
        jnp.sum(jnp.where(fin, 0.0, one), axis=axes),
        jnp.sum(jnp.where(fin & (ax >= hi), one, 0.0), axis=axes),
        jnp.sum(jnp.where(fin & nz & (ax < lo), one, 0.0),
                axis=axes),
    ], axis=-1)


def pooled_raw(leaves: Sequence[Any]) -> jnp.ndarray:
    """(1, NCOL) raw summary pooling several tensors into one family
    (used for the non-stacked remainder so the packed vector stays
    small on models with many scalar leaves)."""
    rows = jnp.stack([leaf_raw(x) for x in leaves])        # (n, NCOL)
    return jnp.stack([rows[:, 0].sum(), rows[:, 1].max(),
                      rows[:, 2].sum(), rows[:, 3].sum(),
                      rows[:, 4].sum()])[None]


def quant_raw(grads: Sequence[Any], ef_in: Sequence[Any],
              ef_out: Sequence[Any]) -> jnp.ndarray:
    """(NQCOL,) raw quantization-error sums for one bucket / leaf
    group. The codec's residual algebra gives ``new_ef = orig − own``
    exactly (orig = grad + carried ef), so

    - relative wire error  rms(dequant−orig)/rms(orig) = √(err/orig)
    - EF magnitude drift   rms(new_ef)/rms(grad)       = √(err/grad)

    both derive host-side from these three sums; an fp32 wire yields
    err_ss ≡ 0."""
    def _ss(xs):
        t = jnp.float32(0.0)
        for x in xs:
            xf = jnp.asarray(x).astype(jnp.float32)
            t = t + jnp.sum(xf * xf)
        return t
    orig = _ss([jnp.asarray(g).astype(jnp.float32)
                + jnp.asarray(e).astype(jnp.float32)
                for g, e in zip(grads, ef_in)])
    return jnp.stack([_ss(ef_out), orig, _ss(grads)])


# -- pytree naming / stacked-entry discovery ----------------------------------

def _key_str(part) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(part, attr):
            return str(getattr(part, attr))
    return str(part)


def _path_name(path) -> str:
    return ".".join(_key_str(p) for p in path)


def _stacked_key_set(tree, stacked_keys=None):
    """Top-level keys whose subtree leaves carry a leading layer dim.
    Explicit list wins; otherwise auto-detect the PR 8 pre-stacked
    entries (gpt ``_stacked_blocks``, bert ``*_stacked_layers``)."""
    if stacked_keys is not None:
        return set(stacked_keys)
    if isinstance(tree, dict):
        return {k for k in tree if isinstance(k, str)
                and (k == "_stacked_blocks"
                     or k.endswith("_stacked_layers"))}
    return set()


def grad_families(grads, stacked_keys=None):
    """Split a grad pytree into ([(name, stacked leaf)], [(name,
    plain leaf)]) — stacked leaves are per-layer families."""
    skeys = _stacked_key_set(grads, stacked_keys)
    flat, _ = jax.tree_util.tree_flatten_with_path(grads)
    stacked, plain = [], []
    for path, leaf in flat:
        name = _path_name(path)
        if path and _key_str(path[0]) in skeys and jnp.ndim(leaf) >= 1:
            stacked.append((name, leaf))
        else:
            plain.append((name, leaf))
    return stacked, plain


def add_grad_tree(pk: "Packer", grads, stacked_keys=None,
                  prefix: str = "grad/"):
    """Add one pytree to a :class:`Packer`: every stacked leaf becomes
    a per-layer family, the remainder pools into ``<prefix>(rest)``."""
    stacked, plain = grad_families(grads, stacked_keys)
    for name, leaf in stacked:
        per_layer = int(np.prod(leaf.shape[1:])) if leaf.ndim > 1 else 1
        pk.family(prefix + name, stacked_raw(leaf), per_layer)
    if plain:
        total = int(sum(int(np.prod(np.shape(l)) or 1)
                        for _, l in plain))
        pk.family(prefix + "(rest)",
                  pooled_raw([l for _, l in plain]), total)


# -- the packed vector --------------------------------------------------------

class Layout:
    """Host-side schema of one packed vector: family/bucket names and
    shapes are static per compilation, so the single harvested array
    decodes without any further device traffic."""

    def __init__(self, families, quants, scalars):
        self.families = list(families)   # (name, L, per-layer count)
        self.quants = list(quants)       # (name, n_buckets)
        self.scalars = list(scalars)     # names
        self.size = (NHDR
                     + sum(L * NCOL for _, L, _ in self.families)
                     + sum(b * NQCOL for _, b in self.quants)
                     + len(self.scalars))

    def family_names(self) -> List[str]:
        return [n for n, _, _ in self.families]

    def unpack(self, arr) -> Optional[dict]:
        """Decode one harvested vector into a JSON-ready snapshot.
        Returns None for an off-cadence (zeroed) sample."""
        a = np.asarray(arr, dtype=np.float64).reshape(-1)
        if a.shape[0] != self.size:
            raise ValueError(
                f"packed size {a.shape[0]} != layout {self.size}")
        if a[0] != 1.0:
            return None
        snap: Dict[str, Any] = {
            "loss": float(a[1]),
            "nonfinite": float(a[2]),
            "first_bad_layer": int(a[3]),
            "first_bad_family": int(a[4]),
        }
        names = self.family_names()
        fam_idx = snap["first_bad_family"]
        snap["first_bad_family_name"] = (
            names[fam_idx] if 0 <= fam_idx < len(names) else None)
        off = NHDR
        fams: Dict[str, Any] = {}
        g_ss = g_n = u_ss = u_n = 0.0
        g_amax = u_amax = over_max = under_max = 0.0
        for name, L, cnt in self.families:
            blk = a[off:off + L * NCOL].reshape(L, NCOL)
            off += L * NCOL
            cnt = max(1, cnt)
            fams[name] = {
                "rms": [float(math.sqrt(max(v, 0.0) / cnt))
                        for v in blk[:, 0]],
                "amax": [float(v) for v in blk[:, 1]],
                "nonfinite": [float(v) for v in blk[:, 2]],
                "overflow_frac": [float(v / cnt) for v in blk[:, 3]],
                "underflow_frac": [float(v / cnt) for v in blk[:, 4]],
            }
            over_max = max(over_max, max(fams[name]["overflow_frac"]))
            under_max = max(under_max,
                            max(fams[name]["underflow_frac"]))
            if name.startswith("upd/"):
                u_ss += float(blk[:, 0].sum()); u_n += cnt * L
                u_amax = max(u_amax, float(blk[:, 1].max()))
            else:
                g_ss += float(blk[:, 0].sum()); g_n += cnt * L
                g_amax = max(g_amax, float(blk[:, 1].max()))
        quants: Dict[str, Any] = {}
        rel_all: List[float] = []
        ef_all: List[float] = []
        for name, b in self.quants:
            blk = a[off:off + b * NQCOL].reshape(b, NQCOL)
            off += b * NQCOL
            rel = [float(math.sqrt(max(e, 0.0) / max(o, 1e-30)))
                   for e, o in zip(blk[:, 0], blk[:, 1])]
            efr = [float(math.sqrt(max(e, 0.0) / max(g, 1e-30)))
                   for e, g in zip(blk[:, 0], blk[:, 2])]
            quants[name] = {"rel_err": rel, "ef_ratio": efr}
            rel_all += rel
            ef_all += efr
        scalars = {n: float(a[off + i])
                   for i, n in enumerate(self.scalars)}
        snap.update({
            "families": fams,
            "quant": quants,
            "scalars": scalars,
            "grad_rms": float(math.sqrt(g_ss / g_n)) if g_n else 0.0,
            "grad_amax": g_amax,
            "update_rms": (float(math.sqrt(u_ss / u_n))
                           if u_n else None),
            "overflow_frac_max": over_max,
            "underflow_frac_max": under_max,
            "quant_rel_err_max": max(rel_all) if rel_all else None,
            "quant_rel_err_mean": (float(np.mean(rel_all))
                                   if rel_all else None),
            "ef_ratio_max": max(ef_all) if ef_all else None,
        })
        return snap


class LayoutBox:
    """Mutable slot a step builder hangs off its compiled step
    (``step.numerics_layout``); :meth:`Packer.pack` fills it as a
    trace-time host side effect, so :class:`Monitor` can decode
    harvests without the builder threading the layout around."""

    def __init__(self):
        self.layout: Optional[Layout] = None


class Packer:
    """Trace-time accumulator for the one-per-step packed vector."""

    def __init__(self):
        self._fams: List[Tuple[str, int, int]] = []
        self._fraw: List[jnp.ndarray] = []
        self._quants: List[Tuple[str, int]] = []
        self._qraw: List[jnp.ndarray] = []
        self._scalars: List[str] = []
        self._sraw: List[jnp.ndarray] = []

    def family(self, name: str, raw, per_layer_count: int):
        raw = jnp.asarray(raw)
        if raw.ndim != 2 or raw.shape[1] != NCOL:
            raise ValueError(f"family raw must be (L,{NCOL}), "
                             f"got {raw.shape}")
        # ptlint: disable=PT001,PT003 -- static shape; the Packer is a
        # per-trace accumulator, discarded with the trace
        self._fams.append((str(name), int(raw.shape[0]),
                           # ptlint: disable=PT001 -- host int
                           int(per_layer_count)))
        # ptlint: disable=PT003 -- same per-trace accumulator
        self._fraw.append(raw.astype(jnp.float32))

    def leaf(self, name: str, x):
        self.family(name, leaf_raw(x)[None],
                    int(np.prod(np.shape(x)) or 1))

    def quant(self, name: str, raw):
        raw = jnp.asarray(raw)
        if raw.ndim != 2 or raw.shape[1] != NQCOL:
            raise ValueError(f"quant raw must be (B,{NQCOL}), "
                             f"got {raw.shape}")
        self._quants.append((str(name), int(raw.shape[0])))
        self._qraw.append(raw.astype(jnp.float32))

    def scalar(self, name: str, val):
        self._scalars.append(str(name))
        self._sraw.append(jnp.asarray(val).astype(jnp.float32)
                          .reshape(()))

    def layout(self) -> Layout:
        return Layout(self._fams, self._quants, self._scalars)

    def pack(self, loss=None, box: Optional[LayoutBox] = None
             ) -> jnp.ndarray:
        """Concatenate header + every family/bucket/scalar into the
        single f32 vector. The provenance header reduces the per-layer
        non-finite counts layer-major, so the FIRST bad layer wins and
        ties break toward the earlier-registered family."""
        F = len(self._fams)
        if F:
            lmax = max(L for _, L, _ in self._fams)
            cols = [jnp.pad(r[:, 2] > 0, (0, lmax - r.shape[0]))
                    for r in self._fraw]
            bad = jnp.stack(cols)                       # (F, lmax)
            flat = bad.T.reshape(-1)                    # layer-major
            any_bad = jnp.any(flat)
            first = jnp.argmax(flat)
            first_layer = jnp.where(any_bad, first // F, -1)
            first_fam = jnp.where(any_bad, first % F, -1)
            nft = sum(jnp.sum(r[:, 2]) for r in self._fraw)
        else:
            first_layer = first_fam = jnp.int32(-1)
            nft = jnp.float32(0.0)
        lossv = (jnp.asarray(loss).astype(jnp.float32).reshape(())
                 if loss is not None else jnp.float32(jnp.nan))
        header = jnp.stack([jnp.float32(1.0), lossv,
                            jnp.asarray(nft, jnp.float32),
                            first_layer.astype(jnp.float32),
                            first_fam.astype(jnp.float32)])
        parts = [header]
        parts += [r.reshape(-1) for r in self._fraw]
        parts += [q.reshape(-1) for q in self._qraw]
        parts += [s.reshape(1) for s in self._sraw]
        packed = jnp.concatenate(parts).astype(jnp.float32)
        if box is not None:
            box.layout = self.layout()
        return packed


def cond_every(step_count, every_k: int, build):
    """Gate ``build()`` (→ packed vector) behind the cadence: at
    every_k>1 the stats subgraph runs under ``lax.cond`` keyed on the
    optimizer step counter and off-cadence steps produce a zeroed
    vector (tag 0.0) without evaluating the stats at all."""
    # ptlint: disable=PT001 -- every_k is a host int (env cadence knob)
    if step_count is None or int(every_k) <= 1:
        return build()
    shape = jax.eval_shape(build)
    # ptlint: disable=PT001 -- same host int
    pred = (jnp.asarray(step_count) % int(every_k)) == 0
    return lax.cond(pred, build,
                    lambda: jnp.zeros(shape.shape, shape.dtype))


def capture_step(grads, *, loss=None, updates=None, step_count=None,
                 stacked_keys=None, box: Optional[LayoutBox] = None
                 ) -> jnp.ndarray:
    """One-call in-graph capture for a plain (jit/GSPMD) train step:
    per-layer grad families (+ optional param-update deltas) packed at
    the PT_NUMERICS_EVERY cadence."""
    def build():
        pk = Packer()
        add_grad_tree(pk, grads, stacked_keys)
        if updates is not None:
            add_grad_tree(pk, updates, stacked_keys, prefix="upd/")
        return pk.pack(loss=loss, box=box)
    return cond_every(step_count, max(1, every()), build)


def split_out(out):
    """Split a step's return into ((params, state, loss), packed|None)
    — builders append the packed vector only when numerics is enabled,
    so callers stay compatible with both shapes."""
    if isinstance(out, (tuple, list)) and len(out) == 4:
        return tuple(out[:3]), out[3]
    return tuple(out), None


# -- fault injection: train.grad_poison ---------------------------------------

def _corrupt_flat(flat, pos, action: str, bit: int):
    """Corrupt one element of a flattened leaf: action 'nan' plants a
    NaN, 'bitflip' XORs an exponent bit (default 30 → a huge-but-
    finite value that trips the amax/overflow detectors instead)."""
    tgt = flat[pos].astype(jnp.float32)
    if action == "bitflip":
        bits = lax.bitcast_convert_type(tgt, jnp.uint32)
        bad = lax.bitcast_convert_type(
            bits ^ jnp.uint32(1 << (bit % 32)), jnp.float32)
    else:
        bad = jnp.float32(jnp.nan)
    return flat.at[pos].set(bad.astype(flat.dtype))


def _poison_rules():
    from paddle_tpu.testing import faults
    if not faults.enabled():
        return []
    return faults.spec(FAULT_SITE, actions=("nan", "bitflip"))


def _rule_gate(kw, step_count):
    """Optional in-graph step gate: kw ``step=k`` scopes the (trace-
    time-armed) corruption to one optimizer step — how the smoke run
    scripts a MID-run poison with a single compilation."""
    if "step" in kw and step_count is not None:
        # ptlint: disable=PT001 -- rule kwargs are host strings
        return jnp.asarray(step_count) == int(kw["step"])
    return None


def poison_grads(grads, stacked_keys=None, step_count=None):
    """Fault site ``train.grad_poison``: inject a NaN/bitflip into one
    layer's gradient IN-GRAPH, before any comm/update consumes it.
    Consulted at trace time (the rule arms per compilation, like the
    wire-fault site); rule kwargs:

    - ``layer=k``  which layer of the stacked leaf (default 0)
    - ``key=sub``  substring selecting the leaf family (default: the
      first stacked family)
    - ``step=s``   corrupt only when the optimizer step counter == s
    - ``bit=b``    exponent bit for action ``bitflip`` (default 30)
    """
    rules = _poison_rules()
    if not rules:
        return grads
    flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
    names = [_path_name(p) for p, _ in flat]
    skeys = _stacked_key_set(grads, stacked_keys)
    is_stacked = [bool(p and _key_str(p[0]) in skeys
                       and jnp.ndim(leaf) >= 1)
                  for p, leaf in flat]
    vals = [leaf for _, leaf in flat]
    for kw in rules:
        key = str(kw.get("key", ""))
        order = ([i for i in range(len(names)) if is_stacked[i]]
                 + [i for i in range(len(names)) if not is_stacked[i]])
        idx = next((i for i in order if key in names[i]), None)
        if idx is None:
            continue
        x = vals[idx]
        gate = _rule_gate(kw, step_count)
        action = str(kw.get("action", "nan"))
        bit = int(kw.get("bit", 30))
        if is_stacked[idx]:
            layer = int(kw.get("layer", 0)) % int(x.shape[0])
            f2 = x.reshape(x.shape[0], -1)
            bad = _corrupt_flat(f2, (layer, 0), action, bit)
        else:
            f2 = x.reshape(-1)
            bad = _corrupt_flat(f2, 0, action, bit)
        if gate is not None:
            bad = jnp.where(gate, bad, f2)
        vals[idx] = bad.reshape(x.shape)
    return jax.tree_util.tree_unflatten(treedef, vals)


def poison_layer_slice(dw: Dict[str, Any], layer_index,
                       step_count=None) -> Dict[str, Any]:
    """Per-layer variant for in-backward scan bodies (the overlap
    step): ``dw`` holds ONE layer's grad slices and ``layer_index`` is
    the traced layer id, so the corruption is a ``where`` on the rule's
    static target layer — the scan body stays uniform."""
    rules = _poison_rules()
    if not rules:
        return dw
    out = dict(dw)
    for kw in rules:
        key = str(kw.get("key", ""))
        name = next((k for k in out if key in k), None)
        if name is None:
            continue
        layer = int(kw.get("layer", 0))
        gate = jnp.asarray(layer_index) == layer
        sgate = _rule_gate(kw, step_count)
        if sgate is not None:
            gate = jnp.logical_and(gate, sgate)
        x = out[name]
        flat = x.reshape(-1)
        bad = _corrupt_flat(flat, 0, str(kw.get("action", "nan")),
                            int(kw.get("bit", 30)))
        out[name] = jnp.where(gate, bad, flat).reshape(x.shape)
    return out


# -- host plane: recorder / watch / monitor -----------------------------------

class NumericsRecorder:
    """Bounded ring of the last-N decoded snapshots; on demand dumps
    them as pid-suffixed atomic JSON (flight-recorder idiom) so the
    steps LEADING INTO a spike survive the postmortem."""

    def __init__(self, capacity: Optional[int] = None):
        self._ring: deque = deque(maxlen=capacity or ring_capacity())

    def append(self, snap: dict):
        # ptlint: disable=PT003 -- host-plane ring, never traced
        self._ring.append(snap)

    def snapshots(self) -> List[dict]:
        return list(self._ring)

    def __len__(self):
        return len(self._ring)

    def dump(self, reason: str, step=None) -> Optional[dict]:
        if not self._ring:
            return None
        rec = {"reason": str(reason),
               "step": int(step) if step is not None else None,
               "dumped_at": time.time(), "pid": os.getpid(),
               "rank": os.environ.get("PT_PROCESS_ID", "0"),
               "snapshots": list(self._ring)}
        stats_lib.add("num/dumps")
        try:
            d = _dump_dir()
            if d:
                os.makedirs(d, exist_ok=True)
                tag = rec["step"] if rec["step"] is not None else "na"
                # pid-suffixed: every rank of a launch shares the dump
                # dir but holds a different view of the blow-up
                path = os.path.join(
                    d, f"numerics_{tag}.{os.getpid()}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(rec, f)
                os.replace(tmp, path)
                rec["path"] = path
            else:
                print("[numerics] " + json.dumps(rec),
                      file=sys.stderr, flush=True)
        except Exception:
            pass
        return rec


class NumericsWatch:
    """Edge-triggered numerics detectors (FleetStats alert idiom: one
    counter tick + one stderr line per incident; re-fires only after
    the condition clears):

    - ``nonfinite``      any non-finite grad/update element; the alert
      names the first bad layer + leaf family from the in-graph
      provenance reduction
    - ``loss_spike``     loss z-score vs windowed median/MAD
    - ``grad_explosion`` grad-rms z-score vs windowed median/MAD
    - ``overflow``       max per-family dtype-overflow fraction
    - ``ef_runaway``     error-feedback drift ratio rms(ef)/rms(grad)

    Any firing detector auto-dumps the recorder ring."""

    def __init__(self, window: Optional[int] = None,
                 z: Optional[float] = None,
                 overflow_frac: Optional[float] = None,
                 ef_ratio: Optional[float] = None,
                 recorder: Optional[NumericsRecorder] = None):
        self.window = int(window
                          or _env_float("PT_NUMERICS_WINDOW", 32))
        self.z = float(z or _env_float("PT_NUMERICS_Z", 6.0))
        self.overflow_frac = float(
            overflow_frac or _env_float("PT_NUMERICS_OVERFLOW", 0.01))
        self.ef_ratio = float(
            ef_ratio or _env_float("PT_NUMERICS_EF", 8.0))
        self.recorder = recorder
        self._loss_hist: deque = deque(maxlen=self.window)
        self._grad_hist: deque = deque(maxlen=self.window)
        self._active: set = set()
        self.alerts: List[dict] = []

    # FleetStats edge-trigger idiom
    def _fire(self, kind: str, key, msg: str) -> bool:
        if key in self._active:
            return False
        self._active.add(key)
        stats_lib.add(f"num/alert_{kind}")
        self.alerts.append({"t": time.time(), "kind": kind,
                            "msg": msg})
        print(f"[numerics] ALERT {kind}: {msg}", file=sys.stderr,
              flush=True)
        return True

    def _clear(self, key):
        self._active.discard(key)

    def _spiked(self, hist, value: float) -> bool:
        """One-sided robust z-score: value above median + z·(1.4826·
        MAD) with a relative MAD floor so a flat history can't make
        every wiggle a spike."""
        if len(hist) < max(4, self.window // 4):
            return False
        med = float(np.median(hist))
        mad = float(np.median(np.abs(np.asarray(hist) - med)))
        sigma = 1.4826 * mad + 0.05 * abs(med) + 1e-12
        return value > med + self.z * sigma

    def observe(self, snap: dict) -> List[str]:
        """Run every detector over one snapshot; returns the kinds
        that fired ON THIS CALL (edge transitions only)."""
        fired: List[str] = []
        step = snap.get("step")

        def fire(kind, msg):
            if self._fire(kind, (kind,), msg):
                fired.append(kind)

        loss = snap.get("loss")
        nonfinite = (snap.get("nonfinite", 0) or 0) > 0 or (
            loss is not None and not math.isfinite(loss))
        if nonfinite:
            fam = snap.get("first_bad_family_name")
            fire("nonfinite",
                 f"non-finite at step {step}: layer "
                 f"{snap.get('first_bad_layer')} family {fam}")
        else:
            self._clear(("nonfinite",))

        if loss is not None and math.isfinite(loss):
            if self._spiked(self._loss_hist, loss):
                fire("loss_spike",
                     f"loss {loss:.6g} spiked vs window median "
                     f"{float(np.median(self._loss_hist)):.6g} "
                     f"at step {step}")
            else:
                self._clear(("loss_spike",))
            self._loss_hist.append(loss)

        grms = snap.get("grad_rms")
        if grms is not None and math.isfinite(grms):
            if self._spiked(self._grad_hist, grms):
                fire("grad_explosion",
                     f"grad rms {grms:.6g} exploded vs window median "
                     f"{float(np.median(self._grad_hist)):.6g} "
                     f"at step {step}")
            else:
                self._clear(("grad_explosion",))
            self._grad_hist.append(grms)

        over = snap.get("overflow_frac_max") or 0.0
        if over > self.overflow_frac:
            fire("overflow", f"dtype overflow fraction {over:.4g} > "
                 f"{self.overflow_frac:.4g} at step {step}")
        else:
            self._clear(("overflow",))

        efr = snap.get("ef_ratio_max")
        if efr is not None and efr > self.ef_ratio:
            fire("ef_runaway", f"error-feedback drift {efr:.4g} > "
                 f"{self.ef_ratio:.4g} at step {step}")
        else:
            self._clear(("ef_runaway",))

        if fired and self.recorder is not None:
            self.recorder.dump(",".join(fired), step=step)
        return fired


class Monitor:
    """Host endpoint of the capture plane. Per sampled step it pays
    exactly ONE device→host transfer (``np.asarray`` on the packed
    vector — outside any jit scope, PT001-clean), decodes it with the
    builder's :class:`Layout`, records ``num/`` gauges, and feeds the
    watch + recorder."""

    def __init__(self, layout=None, every_k: Optional[int] = None,
                 watch: Optional[NumericsWatch] = None,
                 recorder: Optional[NumericsRecorder] = None):
        self._layout_src = layout
        self.every = int(every() if every_k is None else every_k)
        self.recorder = (recorder if recorder is not None
                         else NumericsRecorder())
        self.watch = (watch if watch is not None
                      else NumericsWatch(recorder=self.recorder))
        self.samples = 0

    @classmethod
    def for_step(cls, step_fn, **kw) -> "Monitor":
        """Bind to a builder-produced step (reads the LayoutBox the
        builder hung off it)."""
        return cls(layout=getattr(step_fn, "numerics_layout", None),
                   **kw)

    def _layout(self) -> Optional[Layout]:
        src = self._layout_src
        if isinstance(src, LayoutBox):
            return src.layout
        return src

    def due(self, step: int) -> bool:
        return self.every > 0 and (int(step) % self.every) == 0

    def ingest(self, packed, step: int) -> Optional[dict]:
        """Harvest one sampled step. Off-cadence calls return None
        without touching the device array (no transfer)."""
        if packed is None or not self.due(step):
            return None
        lay = self._layout()
        if lay is None:
            return None
        snap = lay.unpack(np.asarray(packed))   # the ONE transfer
        if snap is None:                        # in-graph cond said no
            return None
        snap["step"] = int(step)
        self.samples += 1
        self._gauges(snap)
        self.recorder.append(snap)
        snap["alerts"] = self.watch.observe(snap)
        return snap

    def _gauges(self, snap: dict):
        stats_lib.add("num/samples")
        sv = stats_lib.set_value
        if math.isfinite(snap["loss"]):
            sv("num/loss", snap["loss"])
        sv("num/nonfinite", snap["nonfinite"])
        sv("num/first_bad_layer", snap["first_bad_layer"])
        sv("num/grad_rms", snap["grad_rms"])
        sv("num/grad_amax", snap["grad_amax"])
        sv("num/overflow_frac", snap["overflow_frac_max"])
        sv("num/underflow_frac", snap["underflow_frac_max"])
        if snap.get("update_rms") is not None:
            sv("num/update_rms", snap["update_rms"])
        if snap.get("quant_rel_err_mean") is not None:
            sv("num/quant_rel_err", snap["quant_rel_err_mean"])
            sv("num/quant_rel_err_max", snap["quant_rel_err_max"])
        if snap.get("ef_ratio_max") is not None:
            sv("num/ef_drift", snap["ef_ratio_max"])
