"""Per-request flight recorder: a bounded ring of request event
timelines, auto-dumped on terminal failures (ISSUE 13 tentpole).

Tracing answers "where did the time go" but must be switched on BEFORE
the interesting request arrives; the flight recorder answers "what
happened to THIS request" after the fact. Engines, the front-end, the
router, and the disaggregation loops append cheap host-side events
(admission verdict, bucket choice, placements, handoff hops, evictions,
retries) keyed by request id; on a terminal failure — deadline
eviction, non-finite poison, ``handoff-failed`` — the request's whole
timeline is dumped as JSON, so a postmortem needs no re-run under
tracing.

Bounds: at most ``PT_FLIGHT_RING`` requests are tracked (FIFO — the
oldest request's timeline is forgotten when a new one needs the slot;
0 disables recording entirely) and at most ``MAX_EVENTS`` events are
kept per request (oldest dropped first). Recording is one short lock
around a deque append — safe inside the serving hot path.

Dumps land as ``flight_<rid>.<pid>.json`` under ``PT_FLIGHT_DIR``
(falling back to ``PT_TRACE_DIR``); with neither set, the record is
emitted as ONE structured stderr line. Every dump ticks
``serve/flight_dumps``. Each process keeps its OWN recorder — a fleet
request's dump holds the events observed by the dumping process (the
router's dump shows placements and retries, a replica's dump its
admissions and evictions), which is why the pid is in the filename:
router and replicas share the launch's dump dir, and both may dump
the same rid."""

import collections
import json
import os
import sys
import threading
import time
from typing import Optional

__all__ = ["FlightRecorder", "default_recorder", "record", "events",
           "dump", "forget", "reset", "MAX_EVENTS"]

_DEFAULT_RING = 256
MAX_EVENTS = 64


def _ring_from_env() -> int:
    try:
        return int(os.environ.get("PT_FLIGHT_RING", str(_DEFAULT_RING)))
    except ValueError:
        return _DEFAULT_RING


def _dump_dir() -> Optional[str]:
    return (os.environ.get("PT_FLIGHT_DIR")
            or os.environ.get("PT_TRACE_DIR"))


class FlightRecorder:
    """Bounded per-request event ring. One module-level instance per
    process (``default_recorder()``); tests may build their own."""

    def __init__(self, capacity: Optional[int] = None,
                 max_events: int = MAX_EVENTS):
        self.capacity = (_ring_from_env() if capacity is None
                         else int(capacity))
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        # rid -> deque[(wall_s, event, attrs)] — insertion order IS the
        # FIFO eviction order (requests are tracked from first event)
        self._reqs = collections.OrderedDict()
        self._pinned: set = set()
        self.dropped = 0

    def pin(self, rid):
        """Exempt ``rid`` from FIFO eviction. The fleet controller's
        synthetic ``"fleet"`` timeline must survive request churn (a
        postmortem needs the scale/drain history however many requests
        came after it); per-rid events still cap at ``max_events``."""
        with self._lock:
            self._pinned.add(rid)

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(self, rid, event: str, **attrs):
        """Append one event to ``rid``'s timeline (no-op for rid=None
        or a disabled recorder)."""
        if rid is None or self.capacity <= 0:
            return
        t = time.time()
        with self._lock:
            dq = self._reqs.get(rid)
            if dq is None:
                while len(self._reqs) >= self.capacity:
                    victim = next((r for r in self._reqs
                                   if r not in self._pinned), None)
                    if victim is None:
                        break
                    del self._reqs[victim]
                    self.dropped += 1
                dq = self._reqs[rid] = collections.deque(
                    maxlen=self.max_events)
            dq.append((t, event, attrs or None))

    def events(self, rid):
        """``rid``'s recorded timeline, oldest first, as JSON-able
        dicts."""
        with self._lock:
            dq = self._reqs.get(rid)
            rows = list(dq) if dq is not None else []
        out = []
        for t, event, attrs in rows:
            row = {"t": t, "event": event}
            if attrs:
                row.update(attrs)
            out.append(row)
        return out

    def forget(self, rid):
        with self._lock:
            self._reqs.pop(rid, None)

    def reset(self, capacity: Optional[int] = None):
        with self._lock:
            self._reqs.clear()
            self.dropped = 0
            if capacity is not None:
                self.capacity = int(capacity)

    def dump(self, rid, reason: str) -> Optional[dict]:
        """Serialize ``rid``'s timeline on a terminal failure. Returns
        the record dict (None when nothing was tracked). Best-effort by
        contract: a failing dump must never take the serving loop down
        with it."""
        evs = self.events(rid)
        if not evs:
            return None
        rec = {"rid": rid, "reason": reason, "dumped_at": time.time(),
               "pid": os.getpid(),
               "rank": os.environ.get("PT_PROCESS_ID", "0"),
               "events": evs}
        from paddle_tpu import stats
        stats.add("serve/flight_dumps")
        try:
            d = _dump_dir()
            if d:
                os.makedirs(d, exist_ok=True)
                safe = "".join(c if c.isalnum() or c in "-_" else "_"
                               for c in str(rid))
                # pid-suffixed: router and replicas share the dump dir
                # (one PT_TRACE_DIR per launch) and each holds a
                # DIFFERENT view of the same request — a bare
                # flight_<rid>.json would let whichever process dumps
                # last destroy the other's postmortem
                path = os.path.join(
                    d, f"flight_{safe}.{os.getpid()}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(rec, f)
                os.replace(tmp, path)
                rec["path"] = path
            else:
                print("[flight] " + json.dumps(rec), file=sys.stderr,
                      flush=True)
        except Exception:
            pass
        return rec


_DEFAULT = FlightRecorder()


def default_recorder() -> FlightRecorder:
    return _DEFAULT


def pin(rid):
    _DEFAULT.pin(rid)


def record(rid, event: str, **attrs):
    _DEFAULT.record(rid, event, **attrs)


def events(rid):
    return _DEFAULT.events(rid)


def dump(rid, reason: str) -> Optional[dict]:
    return _DEFAULT.dump(rid, reason)


def forget(rid):
    _DEFAULT.forget(rid)


def reset(capacity: Optional[int] = None):
    _DEFAULT.reset(capacity)
