"""Comm-exposed-time accounting over span streams (ISSUE 11).

The overlap scheduler's measured target is *exposed* communication —
collective wall time not covered by any concurrent compute span — driven
toward zero. This module is the first-class bookkeeping for that number:

- :func:`exposed_time` — exact interval algebra: the measure of
  ``union(comm)`` minus its intersection with ``union(compute)``.
  Nested spans, overlapping spans, and back-to-back spans all reduce to
  the correct union first, so a comm span fully inside a compute span
  contributes zero and two abutting comm spans are not double-counted.
- :func:`step_overlap` — the same computation fed from the span
  tracer's ring: comm intervals from ``collective/*`` spans, compute
  intervals from ``compute/*`` spans (the train-loop wrapper emits one
  per dispatched step), optionally clipped to a step window.
- :func:`record_step_overlap` — per-step recording into the stats
  registry: ``comm/exposed_s`` (histogram) and ``comm/overlap_frac``
  (gauge, 1 − exposed/comm_busy).

Caveat the numbers inherit from the tracer (see trace.py): in-program
collectives record *issue-time* spans — the host-side dispatch, not the
on-device transfer. Host-side comm (p2p, checkpoint streaming) measures
for real; for on-device truth, feed :func:`exposed_time` intervals from
an XLA profile — the algebra does not care where the spans came from.
The ``train_overlap`` bench row therefore reports these gauges alongside
the measured overlap-on/off step-time delta, which IS on-device truth.
"""

from typing import Iterable, List, Optional, Tuple

__all__ = ["exposed_time", "overlap_fraction", "span_intervals",
           "step_overlap", "record_step_overlap"]

Interval = Tuple[float, float]


def _union(intervals: Iterable[Interval]) -> List[Interval]:
    """Sorted disjoint union; empty/negative intervals drop out."""
    ivs = sorted((float(a), float(b)) for a, b in intervals if b > a)
    out: List[Interval] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def exposed_time(comm: Iterable[Interval],
                 compute: Iterable[Interval]) -> float:
    """Total measure of ``union(comm)`` not covered by any compute
    interval. Intervals are ``(start, end)`` in any consistent unit;
    nested / overlapping / back-to-back intervals are handled exactly
    via the disjoint unions."""
    cu = _union(comm)
    ku = _union(compute)
    exposed = sum(b - a for a, b in cu)
    i = j = 0
    while i < len(cu) and j < len(ku):
        a = max(cu[i][0], ku[j][0])
        b = min(cu[i][1], ku[j][1])
        if b > a:
            exposed -= b - a
        if cu[i][1] < ku[j][1]:
            i += 1
        else:
            j += 1
    return exposed


def _overlap_triple(comm: Iterable[Interval],
                    compute: Iterable[Interval]):
    """(exposed_s, overlap_frac, comm_busy_s) — the one place the
    busy/exposed/fraction arithmetic lives (frac = 1 when there is no
    comm at all: nothing was exposed)."""
    cu = _union(comm)
    busy = sum(b - a for a, b in cu)
    e = exposed_time(cu, compute)
    frac = 1.0 if busy <= 0.0 else 1.0 - e / busy
    return e, frac, busy


def overlap_fraction(comm: Iterable[Interval],
                     compute: Iterable[Interval]) -> float:
    """1 − exposed/comm_busy: the fraction of collective wall time some
    compute span covers. 1.0 when there is no comm at all (nothing was
    exposed)."""
    return _overlap_triple(comm, compute)[1]


def span_intervals(events, prefix: str,
                   window: Optional[Interval] = None) -> List[Interval]:
    """``(t0_s, t1_s)`` intervals of every recorded span whose name
    starts with ``prefix``, from trace-event tuples (see
    ``trace.events()``), optionally clipped to ``window`` (seconds)."""
    out: List[Interval] = []
    for ev in events:
        if ev is None or not ev[0].startswith(prefix):
            continue
        a = ev[1] / 1e9
        b = (ev[1] + ev[2]) / 1e9
        if window is not None:
            a, b = max(a, window[0]), min(b, window[1])
        if b > a:
            out.append((a, b))
    return out


def step_overlap(events=None, comm_prefix: str = "collective/",
                 compute_prefix: str = "compute/",
                 window: Optional[Interval] = None):
    """``(exposed_s, overlap_frac, comm_busy_s)`` from the span tracer:
    comm spans = ``comm_prefix``-named, compute spans =
    ``compute_prefix``-named, optionally clipped to a step ``window``
    (seconds on the trace clock)."""
    if events is None:
        from paddle_tpu.observability import trace
        events, _ = trace.events()
    return _overlap_triple(span_intervals(events, comm_prefix, window),
                           span_intervals(events, compute_prefix, window))


def record_step_overlap(events=None, comm_prefix: str = "collective/",
                        compute_prefix: str = "compute/",
                        window: Optional[Interval] = None):
    """Compute :func:`step_overlap` and record it: ``comm/exposed_s``
    observes into the histogram (per-step distribution), the
    ``comm/overlap_frac`` gauge holds the latest step. Returns the
    triple so callers can report it directly (bench rows)."""
    from paddle_tpu import stats
    e, frac, busy = step_overlap(events, comm_prefix, compute_prefix,
                                 window)
    stats.observe("comm/exposed_s", e)
    stats.set_value("comm/overlap_frac", frac)
    return e, frac, busy
