__version__ = "0.1.0"
full_version = __version__
major, minor, patch = __version__.split(".")
