"""High-level Model API (ref: python/paddle/hapi/model.py — paddle.Model
:1009, fit:1686 with Static/Dynamic adapters :306/:776).

One adapter only: everything compiles through jit. ``prepare`` builds the
jitted train/eval steps (donating params/opt-state so updates are in-place
in HBM); ``fit`` runs the loop with callbacks/metrics.
"""

import time
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.hapi import callbacks as cbks_mod
from paddle_tpu.metric import Metric


def _batch_len(x) -> int:
    # loaders may yield list/tuple-wrapped inputs (train_batch unwraps
    # via inputs[0]); count samples of the actual batch array
    if isinstance(x, (list, tuple)) and x:
        x = x[0]
    try:
        return int(np.shape(x)[0])
    except Exception:
        return 1


class Model:
    """ref: paddle.Model."""

    def __init__(self, network: nn.Module, inputs=None, labels=None):
        self.network = network.tag_paths()
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self._train_step = None
        self._grad_step_fn = None
        self._grad_step = None
        self._apply_grads = None
        self._accum_grads = None
        self._accum_count = 0
        self._eval_step = None
        self._params = None
        self._opt_state = None
        self.stop_training = False

    # -- prepare ---------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        # AMP (≙ paddle.amp.auto_cast/decorate + GradScaler; VERDICT r1
        # item 4). amp_configs: "O1"/"O2" or dict with keys level, dtype,
        # init_loss_scaling, ...
        self._amp_level, self._amp_dtype, self._scaler = "O0", None, None
        self._scaler_state = None
        if amp_configs:
            from paddle_tpu.amp.grad_scaler import GradScaler
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            self._amp_level = amp_configs.get("level", "O1")
            dtype = amp_configs.get("dtype", "bfloat16")
            self._amp_dtype = dtype
            if dtype == "float16":
                # fp16 needs dynamic loss scaling; bf16 does not (TPU-first
                # policy, amp/auto_cast.py module doc)
                kw = {k: v for k, v in amp_configs.items()
                      if k not in ("level", "dtype")}
                self._scaler = GradScaler(**kw)
        params, _ = self.network.split_params()
        # copy: the jitted train step donates params, which must not delete
        # the network's own (aliased) arrays
        self._params = {k: jnp.copy(v) for k, v in params.items()}
        if self._amp_level == "O2":
            dt = jnp.bfloat16 if self._amp_dtype == "bfloat16" \
                else jnp.float16
            self._params = {
                k: v.astype(dt) if jnp.issubdtype(v.dtype, jnp.floating)
                else v for k, v in self._params.items()}
        if optimizer is not None:
            self._opt_state = optimizer.init(self._params)
        if self._scaler is not None:
            self._scaler_state = self._scaler.init_state()
        self._build_steps()

    def _build_steps(self):
        net = self.network
        loss_fn = self._loss
        opt = self._optimizer
        amp_o1 = self._amp_level == "O1"
        amp_dtype = self._amp_dtype
        scaler = self._scaler

        def forward_loss(params, buffers, x, y, key):
            model = net.merge_params({**buffers, **params})
            with nn.stateful(training=True, rng=key) as ctx:
                if amp_o1:
                    from paddle_tpu.amp.auto_cast import auto_cast
                    with auto_cast(dtype=amp_dtype):
                        out = model(x)
                else:
                    out = model(x)
                loss = loss_fn(out, y)
            return loss, (out, ctx.updates)

        def train_step(params, opt_state, buffers, x, y, key):
            (loss, (out, updates)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(params, buffers, x, y, key)
            new_params, new_opt_state = opt.update(grads, opt_state, params)
            return loss, out, new_params, new_opt_state, updates

        def amp_train_step(params, opt_state, scaler_state, buffers, x, y,
                           key):
            """fp16 step with dynamic loss scaling: scale → grad →
            unscale+found_inf → skip-or-apply → scaler update. found_inf is
            computed on the GLOBAL (sharded) grads, so under a mesh every
            shard's non-finites are seen — the psum the reference does by
            hand (hybrid_parallel_optimizer.py:135-149) is implicit in
            SPMD."""
            def scaled(p):
                loss, aux = forward_loss(p, buffers, x, y, key)
                return scaler.scale_loss(loss, scaler_state), (loss, aux)

            (_, (loss, (out, updates))), grads = jax.value_and_grad(
                scaled, has_aux=True)(params)
            grads, found = scaler.unscale_and_check(grads, scaler_state)
            new_params, new_opt_state = opt.update(grads, opt_state, params)
            new_params, new_opt_state = scaler.apply_or_skip(
                new_params, new_opt_state, params, opt_state, found)
            new_scaler = scaler.update_state(scaler_state, found)
            return loss, out, new_params, new_opt_state, new_scaler, updates

        def grad_step(params, buffers, x, y, key):
            (loss, (out, updates)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(params, buffers, x, y, key)
            return loss, out, grads, updates

        def apply_grads(grads, opt_state, params):
            return opt.update(grads, opt_state, params)

        def eval_step(params, buffers, x, y):
            model = net.merge_params({**buffers, **params})
            with nn.stateful(training=False):
                out = model(x)
                loss = loss_fn(out, y) if loss_fn is not None else jnp.zeros(())
            return loss, out

        def predict_step(params, buffers, x):
            model = net.merge_params({**buffers, **params})
            with nn.stateful(training=False):
                return model(x)

        # donate: old params/opt-state buffers are dead after each step —
        # without donation peak HBM doubles on the largest training arrays.
        # train_batch(update=False) must NOT donate (the old buffers stay
        # live), so a non-donating variant is compiled lazily on first use.
        if opt is not None and scaler is not None:
            self._train_step = jax.jit(amp_train_step,
                                       donate_argnums=(0, 1, 2))
        elif opt is not None:
            self._train_step = jax.jit(train_step, donate_argnums=(0, 1))
        else:
            self._train_step = None
        # gradient accumulation (≙ dygraph .grad accumulation: backward runs
        # every batch, update=True gates the optimizer step): compiled lazily
        self._grad_step_fn = grad_step if opt is not None else None
        self._grad_step = None
        self._apply_grads = (jax.jit(apply_grads, donate_argnums=(0, 1, 2))
                             if opt is not None else None)
        self._accum_grads = None
        self._accum_count = 0
        self._eval_step = jax.jit(eval_step)
        self._predict_step = jax.jit(predict_step)

    def _buffers(self):
        return dict(self.network.named_buffers())

    def _sync_network(self):
        """Write current params back into the Module (checkpoint/state_dict)."""
        if self._params is not None:
            self.network = self.network.merge_params(self._params)

    # -- loops -----------------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        from paddle_tpu import random as pt_random
        x = jnp.asarray(inputs[0] if isinstance(inputs, (list, tuple))
                        else inputs)
        y = jnp.asarray(labels[0] if isinstance(labels, (list, tuple))
                        else labels)
        key = pt_random.next_key()
        if update and self._accum_grads is None and self._scaler is not None:
            loss, out, new_p, new_s, new_sc, updates = self._train_step(
                self._params, self._opt_state, self._scaler_state,
                self._buffers(), x, y, key)
            self._params, self._opt_state = new_p, new_s
            self._scaler_state = new_sc
        elif update and self._accum_grads is None:
            # fast path: fused grad+update step with donated params/opt-state
            loss, out, new_p, new_s, updates = self._train_step(
                self._params, self._opt_state, self._buffers(), x, y, key)
            self._params, self._opt_state = new_p, new_s
        else:
            # accumulation path (≙ reference dygraph .grad accumulation,
            # update only gates the optimizer step): grads are summed across
            # update=False calls and averaged at the update=True step
            if self._scaler is not None:
                raise NotImplementedError(
                    "gradient accumulation with fp16 GradScaler is not "
                    "supported; use bf16 (no scaler) or update=True")
            if self._grad_step is None:
                self._grad_step = jax.jit(self._grad_step_fn)
            loss, out, grads, updates = self._grad_step(
                self._params, self._buffers(), x, y, key)
            if self._accum_grads is None:
                self._accum_grads = grads
            else:
                self._accum_grads = jax.tree_util.tree_map(
                    jnp.add, self._accum_grads, grads)
            self._accum_count += 1
            if update:
                n = self._accum_count
                total = jax.tree_util.tree_map(
                    lambda g: g / n, self._accum_grads)
                self._accum_grads, self._accum_count = None, 0
                self._params, self._opt_state = self._apply_grads(
                    total, self._opt_state, self._params)
        if updates:
            self.network = self.network.apply_updates(updates)
        from paddle_tpu.framework import debug as _dbg
        if _dbg.enabled():  # ≙ FLAGS_check_nan_inf per-step sweep
            _dbg.check_nan_inf({"loss": loss, "params": self._params},
                               label="train step outputs")
        metrics = [float(loss)]
        for m in self._metrics:
            res = m.compute(np.asarray(out), np.asarray(y))
            m.update(*[np.asarray(r) for r in (res if isinstance(res, tuple)
                                               else (res,))])
            metrics.append(m.accumulate())
        return metrics[0] if len(metrics) == 1 else metrics

    def eval_batch(self, inputs, labels=None):
        x = jnp.asarray(inputs[0] if isinstance(inputs, (list, tuple))
                        else inputs)
        y = jnp.asarray(labels[0] if isinstance(labels, (list, tuple))
                        else labels)
        loss, out = self._eval_step(self._params, self._buffers(), x, y)
        return float(loss), out

    def predict_batch(self, inputs):
        x = jnp.asarray(inputs[0] if isinstance(inputs, (list, tuple))
                        else inputs)
        return self._predict_step(self._params, self._buffers(), x)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks: Optional[List] = None, accumulate_grad_batches=1,
            num_iters=None):
        """ref: Model.fit (hapi/model.py:1686)."""
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        eval_loader = None
        if eval_data is not None:
            eval_loader = DataLoader(eval_data, batch_size=batch_size) \
                if isinstance(eval_data, Dataset) else eval_data

        cbks = cbks_mod.CallbackList(callbacks or
                                     [cbks_mod.ProgBarLogger(log_freq,
                                                             verbose)])
        cbks.set_model(self)
        cbks.on_begin("train")
        history = []
        it_count = 0
        loss = float("nan")
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(train_loader):
                x, y = batch[0], batch[1]
                cbks.on_batch_begin("train", step, {})
                from paddle_tpu import stats
                from paddle_tpu.observability import trace
                t_step = time.perf_counter()
                with trace.span("train/step", epoch=epoch, step=step):
                    res = self.train_batch(
                        x, y,
                        update=(step + 1) % accumulate_grad_batches == 0)
                dt = time.perf_counter() - t_step
                loss = res[0] if isinstance(res, list) else res
                logs = {"loss": loss, "step": step}
                cbks.on_batch_end("train", step, logs)
                stats.add("hapi/train_steps", 1)
                stats.add("hapi/train_samples", _batch_len(x))
                stats.set_value("hapi/last_loss", float(loss))
                stats.observe("train/step_s", dt)
                stats.set_value("train/ips", _batch_len(x) / dt
                                if dt > 0 else 0.0)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    break
            train_logs = {"loss": loss}  # nan if the loader was empty
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_loader, verbose=0)
                train_logs.update({f"val_{k}": v
                                   for k, v in eval_res.items()})
            history.append(train_logs)
            cbks.on_epoch_end(epoch, train_logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            if self.stop_training or (num_iters is not None
                                      and it_count >= num_iters):
                break
        cbks.on_end("train")
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset
        loader = DataLoader(eval_data, batch_size=batch_size) \
            if isinstance(eval_data, Dataset) else eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            x, y = batch[0], batch[1]
            loss, out = self.eval_batch(x, y)
            losses.append(loss)
            for m in self._metrics:
                res = m.compute(np.asarray(out), np.asarray(y))
                m.update(*[np.asarray(r)
                           for r in (res if isinstance(res, tuple)
                                     else (res,))])
        out_logs = {"loss": float(np.mean(losses))}
        for m in self._metrics:
            out_logs[m.name()] = m.accumulate()
        return out_logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset
        loader = DataLoader(test_data, batch_size=batch_size) \
            if isinstance(test_data, Dataset) else test_data
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(np.asarray(self.predict_batch(x)))
        if stack_outputs:
            return np.concatenate(outs, axis=0)
        return outs

    # -- persistence -----------------------------------------------------------
    def save(self, path, training=True):
        from paddle_tpu.framework.io import save as obj_save
        self._sync_network()
        obj_save(self.network.state_dict(), path + ".pdparams")
        if training and self._opt_state is not None:
            obj_save({"opt": self._opt_state}, path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from paddle_tpu.framework.io import load as obj_load
        state = obj_load(path + ".pdparams")
        self.network.set_state_dict(state, strict=not skip_mismatch)
        params, _ = self.network.split_params()
        # copy: the donating train step must not delete the network's arrays
        self._params = {k: jnp.copy(v) for k, v in params.items()}
        import os
        if not reset_optimizer and os.path.exists(path + ".pdopt") and \
                self._optimizer is not None:
            self._opt_state = obj_load(path + ".pdopt")["opt"]

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from paddle_tpu.hapi.summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)
