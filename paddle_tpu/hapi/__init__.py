from paddle_tpu.hapi.model import Model
from paddle_tpu.hapi.summary import summary, flops
from paddle_tpu.hapi import callbacks

__all__ = ["Model", "summary", "flops", "callbacks"]
