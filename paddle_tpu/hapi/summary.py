"""Model summary / FLOPs (ref: python/paddle/hapi/model_summary.py,
dynamic_flops.py). FLOPs computed exactly from XLA's cost analysis of the
traced program — more faithful than the reference's per-layer-formula
estimates."""

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["summary", "flops"]


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """Prints parameter table; returns {'total_params': .., 'trainable_params': ..}."""
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape))
        rows.append((name, tuple(p.shape), n))
        total += n
        trainable += n
    for name, b in net.named_buffers():
        n = int(np.prod(b.shape))
        rows.append((name + " (buffer)", tuple(b.shape), n))
        total += n
    width = max((len(r[0]) for r in rows), default=20) + 2
    print(f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}")
    print("-" * (width + 32))
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<20}{n:>12,}")
    print("-" * (width + 32))
    print(f"Total params: {total:,}")
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Exact analytical FLOPs from XLA cost analysis of the traced forward."""
    import paddle_tpu.nn as nn

    def fwd(x):
        with nn.stateful(training=False):
            return net(x)

    x = jnp.zeros(input_size, jnp.float32)
    try:
        compiled = jax.jit(fwd).lower(x).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return int(analysis.get("flops", 0))
    except Exception:
        return 0
