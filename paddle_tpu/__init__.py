"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new design with the capabilities of the reference system surveyed in
``SURVEY.md`` (PaddlePaddle ~v2.4), built idiomatically on JAX/XLA/Pallas:

- tracing + XLA compilation instead of per-op kernel dispatch
  (ref: paddle/phi/core/kernel_factory.h:268 per-call dispatch, eliminated);
- GSPMD named-mesh sharding instead of program-rewrite parallel passes
  (ref: python/paddle/distributed/auto_parallel/);
- ICI/DCN collectives scheduled by XLA instead of NCCL process groups
  (ref: paddle/fluid/distributed/collective/ProcessGroup.h:53);
- Pallas kernels where the reference uses hand-written CUDA fusions
  (ref: paddle/fluid/operators/fused/).

Top-level namespaces mirror the reference's user surface
(python/paddle/{tensor,nn,optimizer,amp,autograd,io,static,distributed}).
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.5 ships shard_map under experimental only; the framework
    # targets the stable `jax.shard_map` spelling, including the renamed
    # replication-check kwarg (check_vma, formerly check_rep)
    from jax.experimental.shard_map import shard_map as _shard_map

    def _shard_map_compat(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    # jax < 0.5: the static bound-axis size lives on the axis frame
    def _axis_size(axis_name):
        import math as _math

        if isinstance(axis_name, (tuple, list)):
            return _math.prod(_jax.core.axis_frame(a) for a in axis_name)
        return _jax.core.axis_frame(axis_name)

    _jax.lax.axis_size = _axis_size

try:
    from jax.experimental.pallas import tpu as _pltpu

    if not hasattr(_pltpu, "CompilerParams"):
        # pre-0.5 spelling: TPUCompilerParams
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except ImportError:                      # pallas not present in this build
    pass

from paddle_tpu.version import __version__
from paddle_tpu import flags
from paddle_tpu.flags import get_flags, set_flags
from paddle_tpu import dtypes
from paddle_tpu.dtypes import (
    bfloat16, float16, float32, float64, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128, get_default_dtype, set_default_dtype,
)
from paddle_tpu import random
from paddle_tpu.random import seed, get_rng_state, set_rng_state

# The functional tensor-op surface (ref: python/paddle/tensor/, 314 fns).
from paddle_tpu.tensor import *  # noqa: F401,F403
from paddle_tpu.tensor import __all__ as _tensor_all

from paddle_tpu.framework import (
    Tensor, to_tensor, is_tensor, no_grad, device_count, devices,
    set_device, get_device, grad, value_and_grad, stop_gradient,
)
from paddle_tpu.framework.compat import (
    CPUPlace, CUDAPlace, CUDAPinnedPlace, NPUPlace, TPUPlace, ParamAttr,
    LazyGuard, DataParallel, enable_static, disable_static,
    in_dynamic_mode, is_grad_enabled, set_grad_enabled, check_shape,
    disable_signal_handler, get_cuda_rng_state, set_cuda_rng_state,
    create_parameter, iinfo, reverse)
from paddle_tpu.dtypes import bool_ as bool  # noqa: A001 (ref name)
from paddle_tpu.dtypes import to_dtype as dtype  # ref: paddle.dtype

import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optimizer
import paddle_tpu.autograd as autograd
import paddle_tpu.amp as amp
import paddle_tpu.io as io
import paddle_tpu.metric as metric
import paddle_tpu.distributed as distributed
import paddle_tpu.vision as vision
import paddle_tpu.profiler as profiler
import paddle_tpu.incubate as incubate
import paddle_tpu.static as static
import paddle_tpu.sparse as sparse
import paddle_tpu.quantization as quantization
import paddle_tpu.distribution as distribution
import paddle_tpu.text as text
import paddle_tpu.audio as audio
import paddle_tpu.geometric as geometric
import paddle_tpu.linalg as linalg
import paddle_tpu.fft as fft
import paddle_tpu.signal as signal
import paddle_tpu.stats as stats
import paddle_tpu.observability as observability
import paddle_tpu.onnx as onnx
import paddle_tpu.inference as inference
import paddle_tpu.jit as jit  # callable module: paddle_tpu.jit(fn) / jit.to_static
import paddle_tpu.hub as hub
import paddle_tpu.device as device
import paddle_tpu.reader as reader
import paddle_tpu.dataset as dataset
import paddle_tpu.utils as utils
import paddle_tpu.sysconfig as sysconfig
import paddle_tpu.regularizer as regularizer
import paddle_tpu.cost_model as cost_model
from paddle_tpu.reader import batch
from paddle_tpu.framework.io import save, load
from paddle_tpu.hapi import Model, summary, flops

__all__ = (
    ["__version__", "nn", "optimizer", "autograd", "amp", "io", "metric",
     "distributed", "vision", "profiler", "incubate", "static", "sparse",
     "quantization",
     "distribution", "text", "audio", "geometric", "linalg", "fft", "signal", "stats",
     "observability",
     "onnx", "hub", "device", "reader", "dataset", "utils",
     "sysconfig", "regularizer", "batch", "version", "cost_model",
     "Tensor", "to_tensor", "is_tensor", "jit", "no_grad", "grad",
     "value_and_grad", "stop_gradient", "device_count", "devices",
     "set_device", "get_device", "save", "load", "Model", "summary", "flops",
     "seed", "get_rng_state", "set_rng_state", "get_flags", "set_flags",
     "get_default_dtype", "set_default_dtype", "inference",
     "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "NPUPlace", "TPUPlace",
     "ParamAttr", "LazyGuard", "DataParallel", "enable_static",
     "disable_static", "in_dynamic_mode", "is_grad_enabled",
     "set_grad_enabled", "check_shape", "disable_signal_handler",
     "get_cuda_rng_state", "set_cuda_rng_state", "create_parameter",
     "iinfo", "reverse", "bool", "dtype"]
    + list(_tensor_all)
)
