"""Legacy reader-style datasets (ref: python/paddle/dataset/ — mnist.py,
cifar.py, uci_housing.py, imdb.py, imikolov.py, movielens.py, conll05.py,
wmt14.py/wmt16.py, flowers.py, voc2012.py). Each module exposes
``train()``/``test()`` readers (zero-arg callables yielding samples) that
compose with paddle.reader decorators and paddle.batch.

Zero-egress environment: like the modern datasets (vision/text/audio),
every loader falls back to DETERMINISTIC SYNTHETIC data with the real
sample schema when the source archive is absent — schema parity is what
ported pipelines need; bytes-identical corpora are not reproducible
offline anyway."""

import numpy as np

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "movielens", "conll05", "wmt14", "wmt16", "flowers", "voc2012"]


class _ReaderModule:
    """train()/test() factory over a synthetic-capable sample generator."""

    def __init__(self, make, n_train, n_test):
        self._make = make
        self._n = {"train": n_train, "test": n_test}

    def train(self, *a, **kw):
        def reader():
            yield from self._make("train", self._n["train"])
        return reader

    def test(self, *a, **kw):
        def reader():
            yield from self._make("test", self._n["test"])
        return reader


def _mnist(mode, n):
    from paddle_tpu.vision.datasets import MNIST
    ds = MNIST(mode="train" if mode == "train" else "test")
    for i in range(min(n, len(ds))):
        img, label = ds[i]
        yield np.asarray(img).reshape(-1), int(label)


def _cifar(mode, n, classes=10):
    rs = np.random.RandomState(7 if mode == "train" else 8)
    for _ in range(n):
        label = rs.randint(classes)
        img = (rs.rand(3072) * 0.2 + label / classes).astype(np.float32)
        yield img, int(label)


def _uci_housing(mode, n):
    rs = np.random.RandomState(13 if mode == "train" else 14)
    w = np.linspace(-1, 1, 13)
    for _ in range(n):
        x = rs.rand(13).astype(np.float32)
        y = np.float32(x @ w + 0.1 * rs.randn())
        yield x, np.array([y], np.float32)


def _imdb(mode, n, vocab=5149, seq=64):
    rs = np.random.RandomState(17 if mode == "train" else 18)
    for _ in range(n):
        label = rs.randint(2)
        words = rs.randint(2 + label, vocab, size=rs.randint(8, seq))
        yield list(map(int, words)), int(label)


def _imikolov(mode, n, vocab=2073, ngram=5):
    rs = np.random.RandomState(19 if mode == "train" else 20)
    for _ in range(n):
        yield tuple(int(w) for w in rs.randint(0, vocab, size=ngram))


def _movielens(mode, n):
    rs = np.random.RandomState(23 if mode == "train" else 24)
    for _ in range(n):
        user, movie = rs.randint(1, 6041), rs.randint(1, 3953)
        yield (user, rs.randint(2), rs.randint(7), rs.randint(21),
               movie, [rs.randint(19)], np.float32(1 + rs.randint(5)))


def _conll05(mode, n):
    from paddle_tpu.text.datasets import Conll05st
    ds = Conll05st(mode="train" if mode == "train" else "test",
                   num_samples=n)
    for i in range(len(ds)):
        yield tuple(np.asarray(t) for t in ds[i])


def _wmt(mode, n, src_vocab=30000, tgt_vocab=30000, seq=16):
    rs = np.random.RandomState(29 if mode == "train" else 31)
    for _ in range(n):
        ls, lt = rs.randint(4, seq), rs.randint(4, seq)
        src = [0] + list(map(int, rs.randint(3, src_vocab, ls))) + [1]
        tgt = [0] + list(map(int, rs.randint(3, tgt_vocab, lt))) + [1]
        yield src, tgt[:-1], tgt[1:]


def _flowers(mode, n):
    rs = np.random.RandomState(37 if mode == "train" else 38)
    for _ in range(n):
        label = rs.randint(102)
        img = (rs.rand(3, 32, 32) * 0.2 + label / 102).astype(np.float32)
        yield img, int(label)


def _voc2012(mode, n):
    rs = np.random.RandomState(41 if mode == "train" else 42)
    for _ in range(n):
        img = rs.rand(3, 32, 32).astype(np.float32)
        seg = rs.randint(0, 21, (32, 32)).astype(np.int32)
        yield img, seg


mnist = _ReaderModule(_mnist, 256, 64)
cifar = _ReaderModule(_cifar, 256, 64)
uci_housing = _ReaderModule(_uci_housing, 404, 102)
imdb = _ReaderModule(_imdb, 256, 64)
imikolov = _ReaderModule(_imikolov, 256, 64)
movielens = _ReaderModule(_movielens, 256, 64)
conll05 = _ReaderModule(_conll05, 64, 16)
wmt14 = _ReaderModule(_wmt, 128, 32)
wmt16 = _ReaderModule(_wmt, 128, 32)
flowers = _ReaderModule(_flowers, 128, 32)
voc2012 = _ReaderModule(_voc2012, 64, 16)
def _cifar100_reader(mode, n):
    def reader():
        yield from _cifar(mode, n, classes=100)
    return reader


# cifar100 variants (≙ cifar.train100/test100 return readers)
cifar.train100 = lambda *a, **kw: _cifar100_reader("train", 256)
cifar.test100 = lambda *a, **kw: _cifar100_reader("test", 64)
