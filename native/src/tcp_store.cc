// TCP key-value rendezvous store.
//
// Reference analog: paddle/fluid/distributed/store/tcp_store.cc +
// socket.cpp — the bootstrap KV store behind init_parallel_env()
// (SURVEY §3.5: trainers rendezvous via TCPStore before forming the
// communicator). Same surface: set / get-with-wait / add / delete, a
// thread-per-connection server and a simple length-prefixed binary
// protocol. C ABI only (ctypes bindings, no pybind11).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Cmd : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kDel = 4, kPing = 5 };
enum Status : uint8_t { kOk = 0, kTimeout = 1, kError = 2 };

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;  // closed in ptts_server_stop after join
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;

  void handle(int fd) {
    for (;;) {
      uint8_t cmd;
      if (!read_full(fd, &cmd, 1)) break;
      uint32_t klen;
      if (!read_full(fd, &klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !read_full(fd, &key[0], klen)) break;
      if (cmd == kSet) {
        uint64_t vlen;
        if (!read_full(fd, &vlen, 8)) break;
        std::string val(vlen, '\0');
        if (vlen && !read_full(fd, &val[0], vlen)) break;
        {
          std::lock_guard<std::mutex> g(mu);
          kv[key] = std::move(val);
        }
        cv.notify_all();
        uint8_t st = kOk;
        uint64_t zero = 0;
        if (!write_full(fd, &st, 1) || !write_full(fd, &zero, 8)) break;
      } else if (cmd == kGet) {
        double timeout_s;
        if (!read_full(fd, &timeout_s, 8)) break;
        std::string val;
        uint8_t st = kOk;
        {
          std::unique_lock<std::mutex> g(mu);
          bool ok = cv.wait_for(
              g, std::chrono::duration<double>(timeout_s),
              [&] { return stop.load() || kv.count(key) > 0; });
          if (!ok || stop.load()) {
            st = kTimeout;
          } else {
            val = kv[key];
          }
        }
        uint64_t vlen = val.size();
        if (!write_full(fd, &st, 1) || !write_full(fd, &vlen, 8)) break;
        if (vlen && !write_full(fd, val.data(), vlen)) break;
      } else if (cmd == kAdd) {
        int64_t delta;
        if (!read_full(fd, &delta, 8)) break;
        int64_t result;
        {
          std::lock_guard<std::mutex> g(mu);
          int64_t cur = 0;
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8) {
            memcpy(&cur, it->second.data(), 8);
          }
          result = cur + delta;
          std::string v(8, '\0');
          memcpy(&v[0], &result, 8);
          kv[key] = std::move(v);
        }
        cv.notify_all();
        uint8_t st = kOk;
        uint64_t vlen = 8;
        if (!write_full(fd, &st, 1) || !write_full(fd, &vlen, 8) ||
            !write_full(fd, &result, 8))
          break;
      } else if (cmd == kDel) {
        {
          std::lock_guard<std::mutex> g(mu);
          kv.erase(key);
        }
        uint8_t st = kOk;
        uint64_t zero = 0;
        if (!write_full(fd, &st, 1) || !write_full(fd, &zero, 8)) break;
      } else if (cmd == kPing) {
        uint8_t st = kOk;
        uint64_t zero = 0;
        if (!write_full(fd, &st, 1) || !write_full(fd, &zero, 8)) break;
      } else {
        break;
      }
    }
    // fd is closed by ptts_server_stop after joining this thread — closing
    // here would let the kernel reuse the fd number while stop still tracks
    // it (shutdown on a reused fd would hit an unrelated socket)
  }

  void accept_loop() {
    for (;;) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        if (stop.load()) return;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(mu);
      conn_fds.push_back(fd);
      conns.emplace_back([this, fd] { handle(fd); });
    }
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;  // one request in flight per client
};

}  // namespace

extern "C" {

// Start a server on `port` (0 = ephemeral). Returns handle or null.
void* ptts_server_start(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 128) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  Server* s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread([s] { s->accept_loop(); });
  return s;
}

int ptts_server_port(void* handle) {
  return static_cast<Server*>(handle)->port;
}

void ptts_server_stop(void* handle) {
  Server* s = static_cast<Server*>(handle);
  s->stop.store(true);
  s->cv.notify_all();
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    // unblock handlers stuck in recv(), then JOIN them — detaching would
    // leave threads touching the Server after delete (use-after-free)
    std::lock_guard<std::mutex> g(s->mu);
    for (int fd : s->conn_fds) shutdown(fd, SHUT_RDWR);
  }
  s->cv.notify_all();
  for (auto& t : s->conns)
    if (t.joinable()) t.join();
  for (int fd : s->conn_fds) close(fd);
  delete s;
}

void* ptts_connect(const char* host, int port, double timeout_s) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return nullptr;
  }
  // bounded retry: the server may not be up yet (rendezvous races)
  double waited = 0.0;
  while (connect(fd, reinterpret_cast<sockaddr*>(&addr),
                 sizeof(addr)) != 0) {
    close(fd);
    if (waited >= timeout_s) return nullptr;
    usleep(100000);
    waited += 0.1;
    fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  Client* c = new Client();
  c->fd = fd;
  return c;
}

static int64_t roundtrip(Client* c, uint8_t cmd, const char* key,
                         const void* payload, uint64_t plen, void* out,
                         uint64_t out_cap) {
  std::lock_guard<std::mutex> g(c->mu);
  uint32_t klen = static_cast<uint32_t>(strlen(key));
  if (!write_full(c->fd, &cmd, 1) || !write_full(c->fd, &klen, 4) ||
      !write_full(c->fd, key, klen))
    return -2;
  if (plen && !write_full(c->fd, payload, plen)) return -2;
  uint8_t st;
  uint64_t vlen;
  if (!read_full(c->fd, &st, 1) || !read_full(c->fd, &vlen, 8)) return -2;
  if (vlen > out_cap) {
    // drain to keep the stream aligned
    std::string sink(vlen, '\0');
    read_full(c->fd, &sink[0], vlen);
    return (st == kOk) ? -3 : -1;
  }
  if (vlen && !read_full(c->fd, out, vlen)) return -2;
  if (st == kTimeout) return -1;
  if (st != kOk) return -2;
  return static_cast<int64_t>(vlen);
}

int ptts_set(void* handle, const char* key, const void* val, uint64_t len) {
  Client* c = static_cast<Client*>(handle);
  struct {
    uint64_t len;
  } hdr{len};
  std::string payload(8 + len, '\0');
  memcpy(&payload[0], &hdr.len, 8);
  if (len) memcpy(&payload[8], val, len);
  char dummy[8];
  int64_t r = roundtrip(c, kSet, key, payload.data(), payload.size(), dummy,
                        sizeof(dummy));
  return r >= 0 ? 0 : static_cast<int>(r);
}

// >=0 value length; -1 timeout; -2 io error; -3 out buffer too small.
int64_t ptts_get(void* handle, const char* key, void* out, uint64_t cap,
                 double timeout_s) {
  Client* c = static_cast<Client*>(handle);
  return roundtrip(c, kGet, key, &timeout_s, 8, out, cap);
}

// Atomic add; returns the new value (or INT64_MIN on error).
int64_t ptts_add(void* handle, const char* key, int64_t delta) {
  Client* c = static_cast<Client*>(handle);
  int64_t result;
  int64_t r = roundtrip(c, kAdd, key, &delta, 8, &result, 8);
  return r == 8 ? result : INT64_MIN;
}

int ptts_del(void* handle, const char* key) {
  char dummy[8];
  int64_t r = roundtrip(static_cast<Client*>(handle), kDel, key, nullptr, 0,
                        dummy, sizeof(dummy));
  return r >= 0 ? 0 : static_cast<int>(r);
}

void ptts_close(void* handle) {
  Client* c = static_cast<Client*>(handle);
  close(c->fd);
  delete c;
}

}  // extern "C"
