// Shared-memory ring buffer for the data-loader pipeline.
//
// Reference analog: the multiprocess DataLoader's shared-memory tensor
// transport (python/paddle/fluid/dataloader/dataloader_iter.py:114,611
// _use_shared_memory + paddle/fluid/memory/allocation/mmap_allocator.cc)
// and the C++ feed path paddle/fluid/framework/data_feed.cc. Worker
// processes serialize batches straight into POSIX shared memory; the
// consumer pops without pickling through a multiprocessing.Queue.
//
// Fixed-size slots, MPMC, blocking push/pop with timeout, process-shared
// pthread mutex/condvars. C ABI only (consumed via ctypes — no pybind11).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x50545055524221ULL;  // "PTPURB!"

struct RBHeader {
  uint64_t magic;
  uint32_t nslots;
  uint64_t slot_size;
  pthread_mutex_t mu;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
  uint32_t head;   // next slot to pop
  uint32_t count;  // filled slots
  uint32_t closed; // producer-side close: pops drain then return -3
  // followed by: uint64_t lens[nslots]; then payload slots
};

struct RB {
  RBHeader* h;
  uint64_t* lens;
  char* slots;
  uint64_t map_size;
  char name[256];
};

uint64_t total_size(uint32_t nslots, uint64_t slot_size) {
  return sizeof(RBHeader) + nslots * sizeof(uint64_t) +
         static_cast<uint64_t>(nslots) * slot_size;
}

RB* attach(void* mem, uint64_t map_size, const char* name) {
  RB* rb = new RB();
  rb->h = reinterpret_cast<RBHeader*>(mem);
  rb->lens = reinterpret_cast<uint64_t*>(static_cast<char*>(mem) +
                                         sizeof(RBHeader));
  rb->slots = static_cast<char*>(mem) + sizeof(RBHeader) +
              rb->h->nslots * sizeof(uint64_t);
  rb->map_size = map_size;
  snprintf(rb->name, sizeof(rb->name), "%s", name);
  return rb;
}

void abs_deadline(double timeout_s, timespec* ts) {
  clock_gettime(CLOCK_REALTIME, ts);
  time_t sec = static_cast<time_t>(timeout_s);
  long nsec = static_cast<long>((timeout_s - sec) * 1e9);
  ts->tv_sec += sec;
  ts->tv_nsec += nsec;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Create (and initialize) a named ring. Returns opaque handle or null.
void* ptrb_create(const char* name, uint32_t nslots, uint64_t slot_size) {
  shm_unlink(name);  // stale ring from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t size = total_size(nslots, slot_size);
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  RBHeader* h = reinterpret_cast<RBHeader*>(mem);
  h->nslots = nslots;
  h->slot_size = slot_size;
  h->head = 0;
  h->count = 0;
  h->closed = 0;
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_full, &ca);
  pthread_cond_init(&h->not_empty, &ca);
  h->magic = kMagic;  // last: marks fully initialized
  return attach(mem, size, name);
}

// Open an existing ring (worker side).
void* ptrb_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<uint64_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  RBHeader* h = reinterpret_cast<RBHeader*>(mem);
  if (h->magic != kMagic) {
    munmap(mem, static_cast<uint64_t>(st.st_size));
    return nullptr;
  }
  return attach(mem, static_cast<uint64_t>(st.st_size), name);
}

uint64_t ptrb_slot_size(void* handle) {
  return static_cast<RB*>(handle)->h->slot_size;
}

static int lock_robust(RBHeader* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {  // a worker died holding the lock
    pthread_mutex_consistent(&h->mu);
    return 0;
  }
  return rc;
}

// 0 ok; -1 timeout; -2 payload too large; -3 ring closed.
int ptrb_push(void* handle, const void* data, uint64_t len,
              double timeout_s) {
  RB* rb = static_cast<RB*>(handle);
  RBHeader* h = rb->h;
  if (len > h->slot_size) return -2;
  timespec dl;
  abs_deadline(timeout_s, &dl);
  if (lock_robust(h) != 0) return -4;
  while (h->count == h->nslots && !h->closed) {
    int rc = pthread_cond_timedwait(&h->not_full, &h->mu, &dl);
    if (rc == EOWNERDEAD) {  // lock reacquired after owner died mid-wait
      pthread_mutex_consistent(&h->mu);
    } else if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -3;
  }
  uint32_t slot = (h->head + h->count) % h->nslots;
  memcpy(rb->slots + static_cast<uint64_t>(slot) * h->slot_size, data, len);
  rb->lens[slot] = len;
  h->count += 1;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// >=0: payload length; -1 timeout; -2 out buffer too small; -3 closed+empty.
int64_t ptrb_pop(void* handle, void* out, uint64_t out_cap,
                 double timeout_s) {
  RB* rb = static_cast<RB*>(handle);
  RBHeader* h = rb->h;
  timespec dl;
  abs_deadline(timeout_s, &dl);
  if (lock_robust(h) != 0) return -4;
  while (h->count == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -3;
    }
    int rc = pthread_cond_timedwait(&h->not_empty, &h->mu, &dl);
    if (rc == EOWNERDEAD) {  // lock reacquired after owner died mid-wait
      pthread_mutex_consistent(&h->mu);
    } else if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  uint32_t slot = h->head;
  uint64_t len = rb->lens[slot];
  if (len > out_cap) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  memcpy(out, rb->slots + static_cast<uint64_t>(slot) * h->slot_size, len);
  h->head = (h->head + 1) % h->nslots;
  h->count -= 1;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

// Mark closed: blocked pushes fail, pops drain remaining then return -3.
void ptrb_close_producer(void* handle) {
  RB* rb = static_cast<RB*>(handle);
  if (lock_robust(rb->h) != 0) return;
  rb->h->closed = 1;
  pthread_cond_broadcast(&rb->h->not_empty);
  pthread_cond_broadcast(&rb->h->not_full);
  pthread_mutex_unlock(&rb->h->mu);
}

int ptrb_size(void* handle) {
  return static_cast<int>(static_cast<RB*>(handle)->h->count);
}

void ptrb_close(void* handle, int unlink_shm) {
  RB* rb = static_cast<RB*>(handle);
  char name[256];
  snprintf(name, sizeof(name), "%s", rb->name);
  munmap(rb->h, rb->map_size);
  if (unlink_shm) shm_unlink(name);
  delete rb;
}

}  // extern "C"
