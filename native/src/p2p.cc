// Point-to-point tensor transport for the cross-host pipeline runtime.
//
// Reference analog: the FleetExecutor message bus —
// paddle/fluid/distributed/fleet_executor/message_bus.cc (brpc/gRPC
// messages between Carriers on different hosts) and interceptor.cc (the
// per-task mailbox). The TPU-native re-design keeps the same shape: every
// rank runs one Endpoint (listen socket + reader threads) whose incoming
// messages land in a tag-addressed mailbox; sends are framed writes on a
// cached connection per peer. No protobuf envelope — activations are raw
// bytes framed [u64 tag][u64 len]; schedule semantics live in Python
// (fleet_executor.py), transport stays dumb and fast.
//
// C ABI only (ctypes bindings, no pybind11).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Endpoint {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> readers;
  std::vector<int> reader_fds;
  std::mutex fds_mu;

  // mailbox: tag -> FIFO of payloads
  std::mutex mu;
  std::condition_variable cv;
  std::map<uint64_t, std::deque<std::vector<char>>> mail;

  // cached outgoing connections, keyed "host:port"
  std::mutex out_mu;
  std::map<std::string, int> out_fds;
};

void reader_loop(Endpoint* ep, int fd, size_t slot) {
  for (;;) {
    uint64_t hdr[2];  // tag, len
    if (!read_full(fd, hdr, sizeof(hdr))) break;
    std::vector<char> payload(hdr[1]);
    if (hdr[1] > 0 && !read_full(fd, payload.data(), hdr[1])) break;
    {
      std::lock_guard<std::mutex> lk(ep->mu);
      ep->mail[hdr[0]].push_back(std::move(payload));
    }
    ep->cv.notify_all();
  }
  // invalidate the slot UNDER the mutex before closing: the fd number may
  // be reused by the kernel, and ptpp_destroy must not shutdown() an
  // unrelated live connection through a stale entry
  {
    std::lock_guard<std::mutex> lk(ep->fds_mu);
    ep->reader_fds[slot] = -1;
  }
  close(fd);
}

void accept_loop(Endpoint* ep) {
  for (;;) {
    sockaddr_in peer{};
    socklen_t len = sizeof(peer);
    int fd = accept(ep->listen_fd, reinterpret_cast<sockaddr*>(&peer), &len);
    if (fd < 0) {
      if (ep->stop.load()) return;
      continue;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> lk(ep->fds_mu);
    size_t slot = ep->reader_fds.size();
    ep->reader_fds.push_back(fd);
    ep->readers.emplace_back(reader_loop, ep, fd, slot);
  }
}

}  // namespace

extern "C" {

void* ptpp_create(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* ep = new Endpoint();
  ep->listen_fd = fd;
  ep->port = ntohs(addr.sin_port);
  ep->accept_thread = std::thread(accept_loop, ep);
  return ep;
}

int ptpp_port(void* h) { return static_cast<Endpoint*>(h)->port; }

// Blocks until a message with `tag` arrives; returns its length WITHOUT
// consuming it (pair with ptpp_recv). -1 on timeout.
int64_t ptpp_probe(void* h, uint64_t tag, double timeout_s) {
  auto* ep = static_cast<Endpoint*>(h);
  std::unique_lock<std::mutex> lk(ep->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(timeout_s));
  bool ok = ep->cv.wait_until(lk, deadline, [&] {
    auto it = ep->mail.find(tag);
    return it != ep->mail.end() && !it->second.empty();
  });
  if (!ok) return -1;
  return static_cast<int64_t>(ep->mail[tag].front().size());
}

// Pops the front message for `tag` into buf. Returns length, -1 on
// timeout, -2 if cap is too small (message stays queued).
int64_t ptpp_recv(void* h, uint64_t tag, void* buf, uint64_t cap,
                  double timeout_s) {
  auto* ep = static_cast<Endpoint*>(h);
  std::unique_lock<std::mutex> lk(ep->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(timeout_s));
  bool ok = ep->cv.wait_until(lk, deadline, [&] {
    auto it = ep->mail.find(tag);
    return it != ep->mail.end() && !it->second.empty();
  });
  if (!ok) return -1;
  auto& q = ep->mail[tag];
  auto& msg = q.front();
  if (msg.size() > cap) return -2;
  int64_t n = static_cast<int64_t>(msg.size());
  if (n > 0) memcpy(buf, msg.data(), msg.size());
  q.pop_front();
  return n;
}

// Framed send on a cached connection to host:port. 0 ok, -1 connect
// failure, -2 write failure (connection dropped from the cache so the
// next send redials — the elastic/restart path).
int ptpp_send(void* h, const char* host, int port, uint64_t tag,
              const void* data, uint64_t len) {
  auto* ep = static_cast<Endpoint*>(h);
  std::string key = std::string(host) + ":" + std::to_string(port);
  std::lock_guard<std::mutex> lk(ep->out_mu);
  auto it = ep->out_fds.find(key);
  int fd = (it == ep->out_fds.end()) ? -1 : it->second;
  if (fd < 0) {
    fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
        connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      close(fd);
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ep->out_fds[key] = fd;
  }
  uint64_t hdr[2] = {tag, len};
  if (!write_full(fd, hdr, sizeof(hdr)) ||
      (len > 0 && !write_full(fd, data, len))) {
    close(fd);
    ep->out_fds.erase(key);
    return -2;
  }
  return 0;
}

void ptpp_destroy(void* h) {
  auto* ep = static_cast<Endpoint*>(h);
  ep->stop.store(true);
  shutdown(ep->listen_fd, SHUT_RDWR);
  close(ep->listen_fd);
  if (ep->accept_thread.joinable()) ep->accept_thread.join();
  {
    std::lock_guard<std::mutex> lk(ep->fds_mu);
    for (int fd : ep->reader_fds)
      if (fd >= 0) shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : ep->readers)
    if (t.joinable()) t.join();
  std::lock_guard<std::mutex> lk(ep->out_mu);
  for (auto& kv : ep->out_fds) close(kv.second);
  delete ep;
}

}  // extern "C"
