"""Driver benchmark: flagship GPT train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-repo numbers (BASELINE.md), so vs_baseline is
achieved MFU / 0.35 — the BASELINE.json north-star MFU target.
"""

import json
import sys
import time

import numpy as np


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    table = (("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),
             ("v4", 275e12), ("v3", 123e12))
    for key, val in table:
        if key in kind:
            return val
    return 197e12  # default: v5e bf16 peak


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import gpt

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    if on_tpu:
        trials = [(gpt.gpt3_350m(max_seq_len=1024, remat=True), 16),
                  (gpt.gpt3_350m(max_seq_len=1024, remat=True), 8),
                  (gpt.gpt3_125m(max_seq_len=1024, remat=True), 8)]
        warmup, iters = 3, 10
    else:
        trials = [(gpt.gpt_tiny(), 4)]
        warmup, iters = 2, 5

    last_err = None
    for cfg, batch in trials:
        try:
            model = gpt.GPT(cfg, seed=0)
            opt = optim.AdamW(learning_rate=1e-4, weight_decay=0.01)
            params, opt_state = gpt.init_train_state(model, opt)
            step = gpt.build_train_step(model, opt)
            tokens = jnp.asarray(
                np.random.RandomState(0).randint(
                    0, cfg.vocab_size, (batch, cfg.max_seq_len)), jnp.int32)
            rng = jax.random.PRNGKey(0)

            for _ in range(warmup):
                params, opt_state, loss = step(params, opt_state, tokens,
                                               rng)
            # NB: fetch a scalar to synchronize — on the tunneled PJRT
            # backend block_until_ready does not actually block.
            float(loss)

            t0 = time.perf_counter()
            for _ in range(iters):
                params, opt_state, loss = step(params, opt_state, tokens,
                                               rng)
            float(loss)
            dt = (time.perf_counter() - t0) / iters

            tokens_per_sec = batch * cfg.max_seq_len / dt
            flops = cfg.flops_per_token() * tokens_per_sec
            if cfg.remat:
                flops *= 8.0 / 6.0  # recompute adds ~1 extra forward
            mfu = flops / _peak_flops(jax.devices()[0])
            print(json.dumps({
                "metric": "gpt_350m_tokens_per_sec_per_chip"
                          if cfg.d_model >= 1024 else
                          ("gpt_125m_tokens_per_sec_per_chip"
                           if cfg.d_model >= 768 else
                           "gpt_tiny_tokens_per_sec_cpu"),
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.35, 4),
            }))
            return 0
        except Exception as e:  # OOM etc. → try next config
            last_err = e
            continue
    print(json.dumps({"metric": "bench_failed", "value": 0, "unit": "",
                      "vs_baseline": 0, "error": str(last_err)[:200]}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
