"""Driver benchmark: flagship model train-step throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The reference publishes no in-repo numbers (BASELINE.md), so vs_baseline is
achieved MFU / 0.35 — the BASELINE.json north-star MFU target.

MFU accounting (VERDICT r1 item 1): model FLOPs = analytic 6N + attention
(GPTConfig.flops_per_token) with NO remat credit — recomputed FLOPs are not
useful work. The XLA cost-analysis FLOPs (which DO include rematerialized
compute) are reported alongside in "extra" as hardware utilization.
"""

import json
import sys
import time

import numpy as np

#: bump when row names/semantics change incompatibly — bench_diff
#: refuses (exit 2) to compare snapshots across schema versions
BENCH_SCHEMA_VERSION = 1


def _provenance(jax) -> dict:
    """ISSUE 15 regression sentinel: stamp the snapshot with what
    produced it — schema version, git rev, device fingerprint, the
    flags-registry snapshot and PT_* env overrides, and the
    compile-cache health (the r05 RESOURCE_EXHAUSTED that silently
    killed rows is now a stamped field bench_diff can surface)."""
    import os
    import subprocess
    from paddle_tpu import compile_cache, flags
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or None
    except Exception:
        rev = None
    devs = jax.devices()
    return {
        "schema_version": BENCH_SCHEMA_VERSION,
        "git_rev": rev,
        "captured_unix_s": int(time.time()),
        "device": {
            "kind": getattr(devs[0], "device_kind", "unknown"),
            "platform": jax.default_backend(),
            "n_devices": len(devs),
        },
        "flags": flags.get_flags(),
        "env_overrides": {k: v for k, v in sorted(os.environ.items())
                          if k.startswith("PT_")},
        "compile_cache": compile_cache.status(),
    }


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    table = (("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),
             ("v4", 275e12), ("v3", 123e12))
    for key, val in table:
        if key in kind:
            return val
    return 197e12  # default: v5e bf16 peak


def _sync(x):
    # NB: fetch a scalar to synchronize — on the tunneled PJRT backend
    # block_until_ready does not actually block.
    return float(x)


def _tune_flash(jax, jnp, b, s, heads, dh, dtype, causal=False,
                kv_lens=None, bias=None):
    """Flash-attention block-size sweep on the exact step shapes
    (fwd+bwd), shared by the GPT and BERT benches: the winner persists
    in the autotune cache and every later `flash_attention` trace on
    these shapes picks it up; a warm cache skips the sweep. Returns a
    reportable dict ({'blocks', 'sweep_ms', 'cache_hit'} or
    {'error': ...}) — a silently broken tune must be visible in the
    bench JSON, not degrade the headline MFU invisibly."""
    if jax.default_backend() != "tpu":
        return None
    try:
        from paddle_tpu.ops.pallas.flash_attention import (
            tune_flash_attention)
        rs = np.random.RandomState(7)
        qt, kt, vt = (jnp.asarray(rs.randn(b, s, heads, dh), dtype)
                      for _ in range(3))
        best, timings = tune_flash_attention(
            qt, kt, vt, causal=causal, kv_lens=kv_lens, bias=bias,
            candidates=[(256, 512), (512, 512), (256, 256), (512, 256)],
            iters=2)
        return {"blocks": list(best),
                "sweep_ms": {f"{bq}x{bk}": round(t * 1e3, 2)
                             for (bq, bk), t in timings.items()},
                "cache_hit": not timings}
    except Exception as e:
        return {"error": str(e)[:120]}


def _timed_gpt_train_step(jax, jnp, peak, cfg, batch, warmup, iters):
    """The one single-chip GPT train-step measurement recipe (shared by
    bench_gpt and bench_longctx): build model + bf16-moment AdamW,
    AOT-compile once (the same executable serves cost analysis and the
    timed loop -- a second trace/compile would double the tunnel-side
    compile cost), time, and report tokens/s + MFU. Returns
    (model, metrics). The MULTICHIP sharded-stacked row
    (bench_train_sharded_stacked) keeps its own loop: under a mesh the
    AOT executable is strict about the output→input sharding fixpoint
    donation needs, so it times the jitted step instead."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import gpt

    model = gpt.GPT(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-4, weight_decay=0.01,
                      moment_dtype=jnp.bfloat16)
    # pre-stacked block weights: the scan-over-layers step consumes the
    # state directly instead of stacking (and grad-unstacking) a full
    # copy of every block weight inside the program — the in-trace form
    # OOMed the 1.3B step on 16GB HBM where the unrolled form fit
    use_stacked = (cfg.moe_experts == 0 and cfg.n_layers > 1
                   and bool(pt_flags.get_flag("scan_layers")))
    params, opt_state = gpt.init_train_state(model, opt,
                                             stacked=use_stacked)
    step = gpt.build_train_step(model, opt)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(
            0, cfg.vocab_size, (batch, cfg.max_seq_len)), jnp.int32)
    rng = jax.random.PRNGKey(0)

    tuned = _tune_flash(jax, jnp, batch, cfg.max_seq_len, cfg.n_heads,
                        cfg.head_dim, cfg.dtype, causal=True)

    compiled = step.lower(params, opt_state, tokens, rng).compile()
    try:
        hw_flops = compiled.cost_analysis().get("flops", 0.0)
    except Exception:
        hw_flops = 0.0
    # peak-memory evidence for the fused blockwise CE (the (B,S,V) logits
    # never exist in HBM in either direction): XLA's own analysis of THE
    # executable that will run
    try:
        ma = compiled.memory_analysis()
        step_peak_mb = round((ma.temp_size_in_bytes
                              + ma.output_size_in_bytes) / 2**20)
    except Exception:
        step_peak_mb = None

    for _ in range(warmup):
        params, opt_state, loss = compiled(params, opt_state, tokens, rng)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = compiled(params, opt_state, tokens, rng)
    _sync(loss)
    dt = (time.perf_counter() - t0) / iters

    tokens_per_sec = batch * cfg.max_seq_len / dt
    mfu = cfg.flops_per_token() * tokens_per_sec / peak
    return model, {
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu_model_flops": round(mfu, 4),
        "hw_util_cost_analysis": round(hw_flops / dt / peak, 4)
        if hw_flops else None,
        "step_ms": round(dt * 1e3, 2),
        "step_peak_mb": step_peak_mb,
        "batch": batch,
        "seq": cfg.max_seq_len,
        # which layer-loop form this number was measured with (the
        # scan form compiles ~L-fold faster; PT_FLAGS_SCAN_LAYERS=0
        # restores the unrolled loop for an A/B)
        "scan_layers": bool(pt_flags.get_flag("scan_layers")),
        **({"flash_autotune": tuned} if tuned else {}),
    }


def bench_gpt(jax, jnp, peak):
    """GPT-3 1.3B (north-star config) single-chip train step; falls back to
    350M when HBM is too small."""
    from paddle_tpu.models import gpt

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        # 1.3B on 16GB HBM: bf16 Adam moments + remat + donation.
        # batch 6 first (bigger matmuls -> higher MFU; r05-start b4
        # peaked 8.9GB, so 6 should fit) with b4 as the proven fallback
        trials = [("gpt_1p3b", gpt.gpt3_1p3b(remat=True), 6),
                  ("gpt_1p3b", gpt.gpt3_1p3b(remat=True), 4),
                  ("gpt_350m", gpt.gpt3_350m(max_seq_len=1024, remat=True),
                   16),
                  ("gpt_125m", gpt.gpt3_125m(max_seq_len=1024, remat=True),
                   8)]
        warmup, iters = 3, 10
    else:
        trials = [("gpt_tiny", gpt.gpt_tiny(), 4)]
        warmup, iters = 2, 3

    last_err = None
    for name, cfg, batch in trials:
        try:
            model, m = _timed_gpt_train_step(jax, jnp, peak, cfg, batch,
                                             warmup, iters)
            bench_gpt.model = model  # reused by bench_decode (params
            # already resident on the chip -- the tunnel transfer is slow)
            return {
                "metric": f"{name}_tokens_per_sec_per_chip",
                "value": m.pop("tokens_per_sec"),
                "unit": "tokens/s",
                "vs_baseline": round(m["mfu_model_flops"] / 0.35, 4),
                "extra": m,
            }
        except Exception as e:  # OOM etc. -> try next config
            # keep only the text: the exception's traceback would pin the
            # failed trial's whole train state (helper frame locals) in
            # HBM while the fallback config compiles
            last_err = str(e)
            continue
    return {"metric": "bench_failed", "value": 0, "unit": "",
            "vs_baseline": 0, "error": (last_err or "")[:200]}


def main():
    import os
    import threading

    t_start = time.perf_counter()

    def mark(msg):
        print(f"[bench +{time.perf_counter() - t_start:.0f}s] {msg}",
              file=sys.stderr, flush=True)

    # Device-acquisition watchdog: a wedged tunnel (stale pool lease)
    # blocks jax.devices() indefinitely; the driver must still get ONE
    # JSON line rather than a silent hang.
    acquired = threading.Event()
    timeout_s = float(os.environ.get("PT_DEVICE_TIMEOUT_S", 900))

    def watchdog():
        if not acquired.wait(timeout_s):
            print(json.dumps({
                "metric": "bench_failed", "value": 0, "unit": "",
                "vs_baseline": 0,
                "error": f"device acquisition exceeded {timeout_s:.0f}s "
                         "(TPU tunnel unavailable)"}), flush=True)
            os._exit(1)

    threading.Thread(target=watchdog, daemon=True).start()

    try:
        import jax
        import jax.numpy as jnp
        # persistent compile cache: the expensive tunnel-side compiles
        # (1.3B train step ≈ tens of minutes cold) are paid once; every
        # re-bench afterwards (opportunistic prober, driver end-of-round)
        # loads the cached executable instead. The guarded helper counts
        # flaky cache reads (r05 logged RESOURCE_EXHAUSTED warnings from
        # mid-bench cache reads) into serve/compile_cache_errors and
        # falls back to cold compiles instead of aborting.
        try:
            from paddle_tpu import compile_cache
            compile_cache.enable(
                os.environ.get("PT_XLA_CACHE_DIR",
                               "/root/.cache/pt_xla_cache"))
        except Exception:
            pass  # bench must start even if the helper import fails
        peak = _peak_flops(jax.devices()[0])
    except Exception as e:  # unhealthy runtime must still emit the line
        acquired.set()
        print(json.dumps({
            "metric": "bench_failed", "value": 0, "unit": "",
            "vs_baseline": 0,
            "error": f"device init failed: {str(e)[:160]}"}), flush=True)
        return 1
    acquired.set()
    mark(f"device acquired: {jax.devices()[0]}")

    # selective runs (PT_BENCH_ONLY=bert,resnet50): re-capture specific
    # sub-benches without paying the flagship compile again — the
    # opportunistic-capture path when the tunnel's uptime is uncertain
    only = {s.strip() for s in os.environ.get("PT_BENCH_ONLY", "").split(
        ",") if s.strip()}
    if "decode" in only:
        only.add("gpt")  # bench_decode reuses the flagship run's model
    if only and "gpt" not in only:
        result = {"metric": "partial_bench", "value": 1, "unit": "",
                  "vs_baseline": 0}
    else:
        mark("start gpt")
        result = bench_gpt(jax, jnp, peak)
        mark(f"gpt done: {result.get('metric')}")

    # stay inside the driver's bench budget: skip sub-benches once the
    # clock runs long (the headline metric is already secured)
    # generous default: the driver's end-of-round run must never drop
    # BASELINE rows because a cold flagship compile ate a small budget
    # (the opportunistic prober sets its own tighter budget)
    budget = float(os.environ.get("PT_BENCH_BUDGET_S", 7200))
    extra = result.setdefault("extra", {})
    # cheap BASELINE rows first (~6 min total): a tight budget then
    # truncates the decode suite, not the headline coverage
    # train_quant_comm runs LAST: on multi-device backends its three
    # fp32/int8/fp8 trials are not cheap, and the decode/longctx
    # headline rows must not lose their budget to it
    # bench_serve runs after the decode/longctx headline rows: its four
    # warmup-compiled engines are not cheap, and a tight budget must
    # truncate the NEW row, not the established ladder
    # bench_serve_disagg, bench_fleet_churn, then bench_train_numerics
    # are the newest rows and run LAST (PR 7/9/11/12 budget-truncation
    # rule): a tight budget truncates them, never the established
    # ladder above them
    for sub in (bench_bert, bench_resnet50, bench_ppyoloe, bench_pp,
                bench_decode, bench_longctx, bench_serve,
                bench_train_sharded_stacked, bench_train_quant_comm,
                bench_train_overlap, bench_serve_disagg,
                bench_fleet_churn, bench_train_numerics):
        name = sub.__name__.replace("bench_", "")
        if only and name not in only:
            continue
        if time.perf_counter() - t_start > budget:
            extra[sub.__name__ + "_skipped"] = "bench budget exhausted"
            continue
        try:
            extra.update(sub(jax, jnp, peak))
        except Exception as e:
            extra[sub.__name__ + "_error"] = str(e)[:120]
        mark(f"{sub.__name__} done")

    try:
        result["provenance"] = _provenance(jax)
    except Exception as e:   # provenance must never cost the snapshot
        result["provenance"] = {"schema_version": BENCH_SCHEMA_VERSION,
                                "error": str(e)[:120]}

    print(json.dumps(result))
    return 0 if result["metric"] != "bench_failed" else 1


def bench_resnet50(jax, jnp, peak, smoke=False):
    """ResNet50 train step: imgs/sec + hardware utilization (BASELINE.md
    conv/BN row). BN buffers update through the stateful context.

    smoke=True runs the SAME code path on tiny shapes (CPU-friendly) so
    tests catch API drift before the driver's TPU run (VERDICT r2 weak 1).
    """
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu import nn, optimizer as optim
    from paddle_tpu.nn import functional as F
    from paddle_tpu.vision.models import resnet18, resnet50

    if smoke:
        net = resnet18(num_classes=10).tag_paths()
        batch, img, classes, warmup, iters = 2, 32, 10, 1, 1
    else:
        net = resnet50(num_classes=1000).tag_paths()
        batch, img, classes, warmup, iters = 256, 224, 1000, 2, 5
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                         weight_decay=1e-4)
    params, buffers = net.split_params()
    params = {k: v.astype(jnp.bfloat16)
              if jnp.issubdtype(v.dtype, jnp.floating) and v.ndim == 4
              else v for k, v in params.items()}
    opt_state = opt.init(params)

    def step(params, opt_state, buffers, x, y, key):
        def loss_fn(p):
            model = net.merge_params({**buffers, **p})
            with nn.stateful(training=True, rng=key) as ctx:
                out = model(x)
                loss = F.cross_entropy(out.astype(jnp.float32), y)
            return loss, ctx.updates
        (loss, updates), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_state = opt.update(grads, opt_state, params)
        return new_params, new_state, updates, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    x = jnp.asarray(np.random.RandomState(0).rand(
        batch, 3, img, img), jnp.bfloat16)
    y = jnp.asarray(np.random.RandomState(1).randint(0, classes, (batch,)),
                    jnp.int32)
    key = jax.random.PRNGKey(0)
    compiled = jstep.lower(params, opt_state, buffers, x, y, key).compile()
    try:
        hw_flops = compiled.cost_analysis().get("flops", 0.0)
    except Exception:
        hw_flops = 0.0
    for _ in range(warmup):
        params, opt_state, buffers_u, loss = compiled(
            params, opt_state, buffers, x, y, key)
        buffers = {**buffers, **buffers_u}
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, buffers_u, loss = compiled(
            params, opt_state, buffers, x, y, key)
    _sync(loss)
    dt = (time.perf_counter() - t0) / iters
    return {"resnet50_imgs_per_sec": round(batch / dt, 1),
            "resnet50_hw_util": round(hw_flops / dt / peak, 4)
            if hw_flops else None,
            "resnet50_batch": batch}


def bench_ppyoloe(jax, jnp, peak, smoke=False):
    """PP-YOLOE-s detection train step imgs/sec (BASELINE.md mixed
    conv+attention row). Same padded-COCO-batch shapes as training: the
    gt tensors are padded to a fixed box count so the whole step stays
    one static XLA program (no dynamic shapes on TPU)."""
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu import optimizer as optim
    from paddle_tpu.vision.models import ppyoloe as M

    if smoke:
        model = M.PPYOLOE(num_classes=8, width=8, depth=1).tag_paths()
        batch, img, boxes, warmup, iters = 2, 64, 4, 1, 1
    else:
        model = M.ppyoloe_s(num_classes=80).tag_paths()
        batch, img, boxes, warmup, iters = 32, 640, 32, 2, 5
    opt = optim.Momentum(learning_rate=0.01, momentum=0.9,
                         weight_decay=5e-4)
    params, buffers = model.split_params()
    opt_state = opt.init(params)
    step = M.build_train_step(model, opt)

    rs = np.random.RandomState(0)
    images = jnp.asarray(rs.rand(batch, 3, img, img), jnp.float32)
    wh = rs.rand(batch, boxes, 2) * (img / 2)
    xy = rs.rand(batch, boxes, 2) * (img / 2)
    gt_boxes = jnp.asarray(
        np.concatenate([xy, xy + wh + 4.0], -1), jnp.float32)
    gt_labels = jnp.asarray(
        rs.randint(0, model.num_classes, (batch, boxes)), jnp.int32)
    gt_valid = jnp.asarray(rs.rand(batch, boxes) < 0.6, jnp.bool_)
    key = jax.random.PRNGKey(0)

    compiled = step.lower(params, buffers, opt_state, images, gt_boxes,
                          gt_labels, gt_valid, key).compile()
    try:
        hw_flops = compiled.cost_analysis().get("flops", 0.0)
    except Exception:
        hw_flops = 0.0
    for _ in range(warmup):
        params, opt_state, updates, loss, _parts = compiled(
            params, buffers, opt_state, images, gt_boxes, gt_labels,
            gt_valid, key)
        buffers = {**buffers, **updates}
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, updates, loss, _parts = compiled(
            params, buffers, opt_state, images, gt_boxes, gt_labels,
            gt_valid, key)
    _sync(loss)
    dt = (time.perf_counter() - t0) / iters
    res = {"ppyoloe_s_imgs_per_sec": round(batch / dt, 1),
           "ppyoloe_s_hw_util": round(hw_flops / dt / peak, 4)
           if hw_flops else None,
           "ppyoloe_s_batch": batch,
           "ppyoloe_s_img": img}

    # eval path: forward + matrix-NMS decode compiled as ONE program
    # (VERDICT r4 item 7 — the host-NMS path cannot be served like this)
    try:
        from paddle_tpu import nn

        eval_model = model.merge_params({**buffers, **params})

        @jax.jit
        def eval_fn(im):
            with nn.stateful(training=False):
                cls, reg, centers, strides = eval_model(im)
            return M.decode_predictions_jit(cls, reg, centers, strides,
                                            top_k=100)
        boxes_o, scores_o, labels_o, valid = eval_fn(images)
        _sync(scores_o[0, 0])
        t0 = time.perf_counter()
        e_iters = max(iters, 2)
        for _ in range(e_iters):
            boxes_o, scores_o, labels_o, valid = eval_fn(images)
        _sync(scores_o[0, 0])
        edt = (time.perf_counter() - t0) / e_iters
        res["ppyoloe_s_eval_imgs_per_sec"] = round(batch / edt, 1)
    except Exception as e:
        res["ppyoloe_s_eval_error"] = str(e)[:120]
    return res


def bench_pp(jax, jnp, peak, smoke=False):
    """PP schedule efficiency on ONE chip (VERDICT r2 item 9): both
    stages of a pp=2 GPipe schedule run time-multiplexed on the single
    device, so schedule overhead (bubble rows + the rolling-buffer
    permute) costs real wall-clock and is directly measurable against the
    dense (unpipelined) step over identical weights/FLOPs.

    theoretical bubble = (S-1)/(n_micro+S-1); with dead-row skipping the
    measured overhead should land well below adding the full bubble.
    """
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu.models import gpt

    if smoke:
        cfg = gpt.GPTConfig(vocab_size=128, max_seq_len=16, d_model=32,
                            n_layers=4, n_heads=2, dtype=jnp.float32)
        n_micro, mb, iters = 3, 2, 1
    else:
        cfg = gpt.gpt3_125m(max_seq_len=1024)
        n_micro, mb, iters = 4, 2, 5
    S = 2
    model = gpt.GPT(cfg, seed=0)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (n_micro, mb, cfg.max_seq_len)), jnp.int32)
    stacked = gpt.stack_blocks(model, S)
    # FLOPs-matched comparison: BOTH sides run exactly the transformer
    # blocks over the same pre-embedded activations and differentiate the
    # same stacked-block params (no head/embedding on either side) — the
    # delta is purely schedule overhead (bubble + rolling-buffer permute)
    x0 = model.embed(toks.reshape(n_micro * mb, cfg.max_seq_len))
    x0 = x0.reshape(n_micro, mb, cfg.max_seq_len, -1)
    lps = cfg.n_layers // S

    def fwd_pp(stacked):
        y = gpt.pipelined_apply(stacked, x0, S, skip_dead_rows=True)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def fwd_dense(stacked):
        h = x0.reshape(n_micro * mb, cfg.max_seq_len, -1)

        def body(hh, blk):
            return blk(hh), None
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape((S * lps,) + a.shape[2:]), stacked)
        h, _ = jax.lax.scan(body, h, flat)
        return jnp.sum(h.astype(jnp.float32) ** 2)

    grad_pp = jax.jit(jax.grad(fwd_pp))
    grad_dense = jax.jit(jax.grad(fwd_dense))

    def timeit(fn, *args):
        out = fn(*args)
        _sync(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _sync(jax.tree_util.tree_leaves(out)[0].reshape(-1)[0])
        return (time.perf_counter() - t0) / iters

    t_pp = timeit(grad_pp, stacked)
    t_dense = timeit(grad_dense, stacked)
    t_pp_f = timeit(jax.jit(fwd_pp), stacked)
    t_dense_f = timeit(jax.jit(fwd_dense), stacked)
    bubble_theory = (S - 1) / (n_micro + S - 1)

    # interleaved (vpp=2) variant of the same model: in ONE XLA program
    # fwd/bwd order is the compiler's (see pipelined_apply_interleaved
    # docstring), so this measures the schedule machinery at S·V ring
    # depth; the bubble ÷V claim is proven on the cross-host runtime
    # (tests/test_fleet_executor.py::test_interleaved_bubble_reduction)
    stacked_v, _ = gpt.stack_blocks_interleaved(model, S, 2)

    def fwd_vpp(stacked_v):
        y = gpt.pipelined_apply_interleaved(stacked_v, x0, S, 2)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    t_vpp_f = timeit(jax.jit(fwd_vpp), stacked_v)
    # Measured r3 (125M, pp2, 4 micro, one v5e chip): fwd overhead ~38%,
    # fwd+bwd ~72% (hoisting per-row weight extraction out of the tick
    # scan shaved ~3 points; the rest is the tick-scan adjoint's per-tick
    # weight-grad accumulation). This single-chip emulation is the
    # worst case — on a real pp mesh each rank holds only its stage's
    # grads and dead rows are free wall-clock; the cross-host runtime
    # (distributed/fleet_executor.py, true 1F1B) is the multi-host path.
    return {"pp2_step_ms": round(t_pp * 1e3, 2),
            "pp2_dense_step_ms": round(t_dense * 1e3, 2),
            "pp2_overhead_measured": round(t_pp / t_dense - 1.0, 4),
            "pp2_fwd_overhead_measured": round(t_pp_f / t_dense_f - 1.0, 4),
            "pp2_bubble_theoretical": round(bubble_theory, 4),
            "pp2_vpp2_fwd_overhead": round(t_vpp_f / t_dense_f - 1.0, 4),
            "pp2_micro": n_micro}


def bench_bert(jax, jnp, peak, smoke=False):
    """BERT-base MLM pretrain step tokens/s/chip + MFU (BASELINE.md
    transformer/AMP row)."""
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu import optimizer as optim
    from paddle_tpu.models import bert

    if smoke:
        cfg = bert.BertConfig(vocab_size=128, d_model=32, n_heads=2,
                              n_layers=2, max_position=32, dropout=0.0)
    else:
        cfg = bert.bert_base(max_position=512, dropout=0.0)
    model = bert.BertForPretraining(cfg, seed=0)
    opt = optim.AdamW(learning_rate=1e-4, weight_decay=0.01,
                      moment_dtype=jnp.bfloat16)
    params, opt_state = bert.init_train_state(model, opt)
    b, s = (2, 16) if smoke else (32, 512)
    # vocab head only at masked positions (15% of s, rounded up to an
    # MXU-friendly slot count)
    step = bert.build_pretrain_step(model, opt, max_predictions=s // 4)
    rs = np.random.RandomState(0)
    tokens = jnp.asarray(rs.randint(0, cfg.vocab_size, (b, s)), jnp.int32)
    type_ids = jnp.zeros((b, s), jnp.int32)
    attn = jnp.ones((b, s), jnp.int32)
    labels = jnp.asarray(
        np.where(rs.rand(b, s) < 0.15,
                 rs.randint(0, cfg.vocab_size, (b, s)), -100), jnp.int32)
    nsp = jnp.asarray(rs.randint(0, 2, (b,)), jnp.int32)
    rng = jax.random.PRNGKey(0)
    args = (tokens, type_ids, attn, labels, nsp, rng)

    tuned = None
    if not smoke:
        # block-size autotune on the encoder's exact attention shapes
        # (VERDICT r3 item 8); shared helper with the GPT bench
        tuned = _tune_flash(jax, jnp, b, s, cfg.n_heads,
                            cfg.d_model // cfg.n_heads, jnp.bfloat16,
                            kv_lens=jnp.full((b,), s, jnp.int32),
                            bias=jnp.zeros((b, 1, 1, s), jnp.float32))

    compiled = step.lower(params, opt_state, *args).compile()
    for _ in range(2):
        params, opt_state, loss = compiled(params, opt_state, *args)
    _sync(loss)
    t0 = time.perf_counter()
    iters = 5
    for _ in range(iters):
        params, opt_state, loss = compiled(params, opt_state, *args)
    _sync(loss)
    dt = (time.perf_counter() - t0) / iters
    tps = b * s / dt
    mfu = cfg.flops_per_token() * tps / peak
    out = {"bert_base_tokens_per_sec_per_chip": round(tps, 1),
           "bert_base_mfu": round(mfu, 4)}
    if tuned is not None:
        out["bert_flash_autotune"] = tuned
    return out


def bench_longctx(jax, jnp, peak, smoke=False):
    """Long-context train step (SURVEY §5.7): GPT-350M at 4k/8k tokens,
    flash-attention path + remat — tokens/s/chip and MFU per sequence
    length. MFU holding up as seq grows is the whole point of the online-
    softmax kernel (attention FLOPs grow quadratically and are counted)."""
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu.models import gpt

    # bench_decode (which needed the flagship weights) has already run:
    # release the ~2.6GB 1.3B model before compiling the 4k/8k trials
    if hasattr(bench_gpt, "model"):
        del bench_gpt.model

    out = {}
    trials = (((64, 2),) if smoke else ((4096, 2), (8192, 1)))
    for seq, batch in trials:
        try:
            cfg = (gpt.gpt_tiny(max_seq_len=seq) if smoke
                   else gpt.gpt3_350m(max_seq_len=seq, remat=True))
            model, m = _timed_gpt_train_step(jax, jnp, peak, cfg, batch,
                                             warmup=2, iters=3)
            out[f"longctx_{seq}_tokens_per_sec"] = m["tokens_per_sec"]
            out[f"longctx_{seq}_mfu"] = m["mfu_model_flops"]
            # release this trial's train state before the next sequence
            # length compiles (stacking two 350M states on top OOMs)
            del model, m
        except Exception as e:
            out[f"longctx_{seq}_error"] = str(e)[:120]
    return out


def bench_decode(jax, jnp, peak, smoke=False):
    """KV-cache autoregressive decode throughput (serving path). Reuses the
    train bench's model so the 2.6GB param transfer over the tunnel is not
    paid twice."""
    model = getattr(bench_gpt, "model", None)
    if model is None or (jax.default_backend() in ("cpu",) and not smoke):
        return {}
    cfg = model.cfg
    import os
    sections = {s.strip() for s in os.environ.get(
        "PT_DECODE_SECTIONS",
        "generate,int8,engine,engine_longctx,engine_paged,"
        "engine_paged_prefix,engine_int8,spec,spec_paged").split(",")}
    b, s0, new = (2, 8, 4) if smoke else (8, 128, 64)
    res = {"decode_batch": b, "decode_prefill": s0, "decode_new": new}
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (b, s0)),
        jnp.int32)
    name = "1p3b" if cfg.d_model >= 2048 else "gpt"
    out = None
    if "generate" in sections:
        out = model.generate(tokens, max_new_tokens=new, max_len=s0 + new)
        _sync(out[0, -1])  # warm/compile
        t0 = time.perf_counter()
        out = model.generate(tokens, max_new_tokens=new, max_len=s0 + new)
        _sync(out[0, -1])
        dt = time.perf_counter() - t0
        res[f"decode_{name}_tokens_per_sec"] = round(b * new / dt, 1)

    # weight-only int8 serving path (decode is HBM-bandwidth bound: int8
    # weights are the dominant read); token agreement needs the baseline
    # generate output
    if "int8" in sections:
      try:
        from paddle_tpu import quantization as quant
        qmodel = quant.quantize_for_inference(model)
        qout = qmodel.generate(tokens, max_new_tokens=new, max_len=s0 + new)
        _sync(qout[0, -1])
        t0 = time.perf_counter()
        qout = qmodel.generate(tokens, max_new_tokens=new, max_len=s0 + new)
        _sync(qout[0, -1])
        qdt = time.perf_counter() - t0
        res[f"decode_{name}_int8_tokens_per_sec"] = round(b * new / qdt, 1)
        # agreement over GENERATED tokens only (the prompt is verbatim in
        # both outputs and would floor the metric at s0/(s0+new)). Greedy
        # decode cascades the first flipped token, so ALSO report logit
        # cosine — the direct quantization-fidelity number. Needs the
        # baseline generate output; the rest of the section does not.
        if out is not None:
            res["decode_int8_token_agreement"] = round(float(
                (np.asarray(qout)[:, s0:]
                 == np.asarray(out)[:, s0:]).mean()), 4)
        lg_d = jax.jit(lambda t: model(t))(tokens).astype(jnp.float32)
        lg_q = jax.jit(lambda t: qmodel(t))(tokens).astype(jnp.float32)
        num = jnp.sum(lg_d * lg_q, axis=-1)
        den = (jnp.linalg.norm(lg_d, axis=-1)
               * jnp.linalg.norm(lg_q, axis=-1) + 1e-9)
        res["decode_int8_logit_cosine"] = round(float(jnp.mean(num / den)),
                                                5)
        # free the quantized weight copy + full-vocab logit arrays before
        # the engine sections measure against the roofline — leftover HBM
        # pressure depresses those numbers
        del qmodel, qout, lg_d, lg_q, num, den
      except Exception as e:
          res["decode_int8_error"] = str(e)[:120]

    # continuous-batching engine throughput vs the HBM roofline (VERDICT
    # r4 item 2: r02's generate-loop decode sat at ~43% of roofline).
    # Both engines are built FIRST (sharing one stacked weight copy),
    # then the unstacked model is dropped: a serving deployment doesn't
    # keep a redundant 2.6GB param copy resident while decoding, and the
    # extra HBM pressure depresses the measurement.
    eng = eng2 = eng8 = roof = None
    slots, s_pf, n_new2 = (2, 8, 4) if smoke else (8, 128, 128)
    spec_k = 4
    from paddle_tpu.inference.decode_engine import (
        DecodeEngine, decode_roofline_tokens_per_sec)
    if "engine" in sections:
      try:
        # chunked device-side stepping: one dispatch per 64
        # tokens/slot — without it, host/tunnel dispatch latency
        # (not the model) bounds the measurement. Cache sized to
        # the workload exactly (T = 256, a 128-multiple): decode is
        # HBM-bound and every padded cache block beyond the valid
        # lengths that still gets fetched is wasted bandwidth.
        eng = DecodeEngine(model, max_slots=slots,
                           max_len=s_pf + n_new2,
                           steps_per_call=2 if smoke else 64)
      except Exception as e:
        res["decode_engine_error"] = str(e)[:160]
    if "spec" in sections:
      try:
        # chunked speculative stepping: drafts + verify + acceptance run
        # device-side, 16 spec iterations per dispatch
        eng2 = DecodeEngine(model, max_slots=slots,
                            max_len=s_pf + n_new2 + 128 + spec_k,
                            speculative_k=spec_k,
                            steps_per_call=2 if smoke else 16,
                            share_weights_with=eng)
      except Exception as e:
        res["decode_spec_error"] = str(e)[:160]
    want_int8 = "engine_int8" in sections
    want_longctx = "engine_longctx" in sections and not smoke
    want_paged = "engine_paged" in sections and not smoke
    want_pfx = "engine_paged_prefix" in sections and not smoke
    if (want_int8 or want_longctx or want_paged or want_pfx) \
            and eng is None and eng2 is None:
      try:  # these sections need a bf16 donor stack even without 'engine'
        eng = DecodeEngine(model, max_slots=slots, max_len=s_pf + n_new2,
                           steps_per_call=2 if smoke else 64)
      except Exception as e:
        res["decode_engine_int8_error"] = str(e)[:160]
        want_int8 = want_longctx = want_paged = want_pfx = False
    if eng is not None or eng2 is not None:
        if getattr(bench_gpt, "model", None) is model:
            del bench_gpt.model
        del model

    def _time_engine(e, prompt_lens=None):
        """Warm (compiles + prefill), then time a drain of n_new2 tokens
        per slot — admissions excluded. Returns (tok/s, dispatches,
        tokens, wall_s)."""
        rs = np.random.RandomState(1)
        lens = prompt_lens or [s_pf] * slots
        prompts = [rs.randint(0, cfg.vocab_size, n) for n in lens]
        for p in prompts:
            e.submit(p, max_new_tokens=2)
        e.run()
        reqs = [e.submit(p, max_new_tokens=n_new2) for p in prompts]
        e.step()
        pre = sum(len(r.tokens) for r in reqs)
        d0 = e.steps
        t0 = time.perf_counter()
        e.run()
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in reqs) - pre
        return toks / dt, e.steps - d0, toks, dt

    def _prof_rows(e, key, tps, disp, toks, wall):
        """ISSUE 15 device-time attribution per engine row: AOT
        cost-analysis roofline + launch tax. Own try/except — the
        timed row must survive a profiler failure."""
        try:
            from paddle_tpu.observability import devprof
            cap = e.dispatch_cost(name=key)
            aroof = devprof.roofline_tokens_per_sec(
                cap, toks / max(1, disp))
            res[f"{key}_flops_per_dispatch"] = cap.flops
            res[f"{key}_hbm_bytes_per_dispatch"] = cap.hbm_bytes
            if aroof > 0:
                res[f"{key}_roofline_frac"] = round(
                    devprof.record_roofline(key, tps, aroof), 4)
            res[f"{key}_launch_tax_frac"] = round(
                devprof.launch_tax_fraction(disp, wall, name=key), 4)
            # kernel launches per generated token (ISSUE 19): pallas
            # launches in the dispatch program (scan-trip weighted,
            # counted from the jaxpr without executing) — the
            # single-dispatch megakernel claim as a LOWER-direction
            # ladder row, with the per-step count alongside (mega
            # paged step = 2: layer-folded kernel + sampling epilogue)
            try:
                fn, fargs = e.dispatch_fn_args()
                lpc = devprof.count_pallas_launches(fn, *fargs)
                res[f"{key}_launches_per_step"] = round(
                    lpc / max(1, e.chunk), 2)
                res[f"{key}_launches_per_token"] = round(
                    lpc * disp / max(1, toks), 4)
            except AttributeError:  # engine without dispatch_fn_args
                res[f"{key}_launches_per_token"] = round(
                    disp / max(1, toks), 4)
        except Exception as ex:
            res[f"{key}_prof_error"] = str(ex)[:120]

    try:
      if eng is not None and "engine" in sections:
        tps, disp, toks, wall = _time_engine(eng)
        hbm = _hbm_gbps(jax.devices()[0])
        roof = decode_roofline_tokens_per_sec(
            cfg, slots, s_pf + n_new2 // 2, hbm)
        res["decode_engine_tokens_per_sec"] = round(tps, 1)
        res["decode_engine_dispatches"] = disp  # timed run only
        res["decode_engine_vs_roofline"] = round(tps / roof, 4)
        res["decode_roofline_tokens_per_sec"] = round(roof, 1)
        _prof_rows(eng, "decode_engine", tps, disp, toks, wall)
    except Exception as e:
        res["decode_engine_error"] = str(e)[:160]

    engL = None
    try:
      if want_longctx:
        donor = eng if eng is not None else eng2
        # ragged long-cache serving: mixed 128/896-token prompts in a
        # T=1024 cache — the flash-decode kernel route (cache length >=
        # decode_kernel_min_t) reads each slot's valid prefix blocks
        # only, so short slots don't pay for long ones (the einsum path
        # reads the whole cache for every slot)
        lens_lc = [128 if i % 2 == 0 else 896 for i in range(slots)]
        engL = DecodeEngine(None, max_slots=slots, max_len=1024,
                            steps_per_call=64, share_weights_with=donor)
        tps, _, _, _ = _time_engine(engL, prompt_lens=lens_lc)
        ctx_mean = sum(lens_lc) / slots + n_new2 // 2
        roof_lc = decode_roofline_tokens_per_sec(
            cfg, slots, ctx_mean, _hbm_gbps(jax.devices()[0]))
        res["decode_engine_longctx_tokens_per_sec"] = round(tps, 1)
        res["decode_engine_longctx_vs_roofline"] = round(tps / roof_lc, 4)
    except Exception as e:
        res["decode_engine_longctx_error"] = str(e)[:160]
    finally:
        if engL is not None:
            # the T=1024 caches must not pressure the int8/spec timings
            engL.kc = engL.vc = None
            del engL

    try:
      if want_paged and (eng is not None or eng2 is not None):
        # paged serving engine on the same workload: first on-hardware
        # exercise of the block-table kernel; memory claim = pages for
        # live tokens only (vs slots x max_len in the contiguous engine)
        from paddle_tpu.inference.paged_engine import PagedDecodeEngine
        engP = PagedDecodeEngine(
            None, n_pages=slots * ((s_pf + n_new2) // 128 + 1) + 2,
            max_slots=slots, steps_per_call=64,
            share_weights_with=(eng if eng is not None else eng2))
        tps, disp, toks, wall = _time_engine(engP)
        res["decode_engine_paged_tokens_per_sec"] = round(tps, 1)
        if roof is None:
            roof = decode_roofline_tokens_per_sec(
                cfg, slots, s_pf + n_new2 // 2,
                _hbm_gbps(jax.devices()[0]))
        res["decode_engine_paged_vs_roofline"] = round(tps / roof, 4)
        _prof_rows(engP, "decode_engine_paged", tps, disp, toks, wall)
        engP.kp = engP.vp = None
        del engP
    except Exception as e:
        res["decode_engine_paged_error"] = str(e)[:160]

    try:
      if want_pfx and (eng is not None or eng2 is not None):
        # paged_prefix ladder row (ISSUE 6): shared-system-prompt
        # workload. Every slot's prompt = one page-aligned 128-token
        # shared prefix + a distinct 32-token tail; the cold round
        # registers the prefix chain in the radix cache, the warm round
        # (same prefix, NEW tails) must prefill only the tails. A
        # prefix-cache regression shows up as hit_tokens collapsing and
        # the warm/cold admission+drain speedup falling toward 1.0.
        from paddle_tpu.inference.paged_engine import PagedDecodeEngine
        from paddle_tpu import stats as _stats
        page, tail = 128, 32
        need = page + tail + n_new2
        engPP = PagedDecodeEngine(
            None, n_pages=2 + slots * (need // page + 3) + 4,
            max_slots=slots, steps_per_call=64,
            share_weights_with=(eng if eng is not None else eng2))
        rs = np.random.RandomState(3)
        shared = list(rs.randint(0, cfg.vocab_size, page))
        # compile warm-up on a TRIE-DISJOINT prefix at the exact timed
        # geometry: the first submit traces the full prefill (cold
        # shape), the second — same warm prefix, new tail — traces the
        # suffix prefill (warm shape), so the timed rounds measure
        # prefill/decode work rather than jit compilation
        warm_pfx = list(rs.randint(0, cfg.vocab_size, page))
        for _ in range(2):
            engPP.submit(
                warm_pfx + list(rs.randint(0, cfg.vocab_size, tail)),
                max_new_tokens=n_new2)
            engPP.run()

        def _prefix_round(prompts):
            _stats.reset("serve/prefix")
            t0 = time.perf_counter()
            reqs = [engPP.submit(p, max_new_tokens=n_new2)
                    for p in prompts]
            engPP.run()
            dt = time.perf_counter() - t0
            toks = sum(len(r.tokens) for r in reqs)
            hits = int(_stats.snapshot("serve/prefix").get(
                "serve/prefix_hit_tokens", 0))
            return toks / dt, hits

        # registration pass (untimed): make the shared chain canonical
        # BEFORE the timed rounds. Admission is sequential, so timing a
        # round that also registers would leave only slot 0 cold —
        # slots 1..N hit the chain slot 0 just registered and the
        # "cold" number would be mostly warm.
        engPP.submit(shared + list(rs.randint(0, cfg.vocab_size, tail)),
                     max_new_tokens=2)
        engPP.run()
        # cold baseline: per-slot DISJOINT prefixes — every admission
        # prefills its full prompt (hit_tokens stays 0)
        tps_cold, _ = _prefix_round(
            [list(rs.randint(0, cfg.vocab_size, page + tail))
             for _ in range(slots)])
        # warm round: the shared prefix + fresh tails — only the tails
        # prefill, every shared token served from the radix cache
        tps_warm, hits = _prefix_round(
            [shared + list(rs.randint(0, cfg.vocab_size, tail))
             for _ in range(slots)])
        res["decode_engine_paged_prefix_tokens_per_sec"] = round(
            tps_warm, 1)
        res["decode_engine_paged_prefix_cold_tokens_per_sec"] = round(
            tps_cold, 1)
        res["decode_engine_paged_prefix_hit_tokens"] = hits
        res["decode_engine_paged_prefix_hit_rate"] = round(
            hits / (slots * (page + tail)), 4)
        engPP.kp = engPP.vp = None
        del engPP
    except Exception as e:
        res["decode_engine_paged_prefix_error"] = str(e)[:160]

    try:
      if want_int8 and (eng is not None or eng2 is not None):
        # built only AFTER the bf16 engine's timed run so its int8 copy
        # + caches add no HBM pressure to that measurement; quantizes
        # from the shared stack (donor untouched, no unstacked model
        # needed)
        donor = eng if eng is not None else eng2
        if eng is not None:
            eng.kc = eng.vc = None   # caches freed, stack stays shared
        eng8 = DecodeEngine(None, max_slots=slots,
                            max_len=s_pf + n_new2,
                            steps_per_call=2 if smoke else 64,
                            share_weights_with=donor,
                            weight_dtype="int8")
        del eng
        eng = None
        tps, _, _, _ = _time_engine(eng8)
        if roof is None:
            roof = decode_roofline_tokens_per_sec(
                cfg, slots, s_pf + n_new2 // 2,
                _hbm_gbps(jax.devices()[0]))
        res["decode_engine_int8_tokens_per_sec"] = round(tps, 1)
        # vs the BF16 roofline on purpose: int8 weights halve the
        # dominant read, so >1.0 is the success signal
        res["decode_engine_int8_vs_bf16_roofline"] = round(tps / roof, 4)
        eng8.kc = eng8.vc = eng8._stacked = None
        del eng8
    except Exception as e:
        res["decode_engine_int8_error"] = str(e)[:160]
    if eng is not None:
        # free the baseline engine's KV caches before the speculative
        # run (the stacked weights are shared with eng2 and stay)
        eng.kc = eng.vc = None
        del eng

    # speculative decoding on repetition-heavy text (the regime it
    # serves): lossless greedy, so the only change is steps-per-token.
    # Own try/except: a spec regression must not erase the baseline
    # metrics (nor vice versa).
    try:
      if eng2 is not None:
        rs = np.random.RandomState(2)
        loops = [list(rs.randint(0, cfg.vocab_size, 8)) for _ in
                 range(slots)]
        sp_prompts = [(lp * (s_pf // 8 + 1))[:s_pf] for lp in loops]
        for p in sp_prompts:  # warm
            eng2.submit(p, max_new_tokens=2)
        eng2.run()
        # in smoke the chunked first step could drain a 4-token budget
        # entirely, leaving nothing in the timed window
        n_spec = n_new2 if not smoke else 12
        reqs2 = [eng2.submit(p, max_new_tokens=n_spec)
                 for p in sp_prompts]
        eng2.step()
        pre2 = sum(len(r.tokens) for r in reqs2)
        s0_steps = eng2.steps
        t0 = time.perf_counter()
        eng2.run()
        sdt = time.perf_counter() - t0
        toks2 = sum(len(r.tokens) for r in reqs2) - pre2
        res["decode_spec_tokens_per_sec"] = round(toks2 / sdt, 1)
        # accepted tokens per device verify ITERATION (each iteration
        # reads the weights once — the HBM-amortization claim); the
        # denominator includes idle tail iterations inside chunks
        res["decode_spec_tokens_per_step"] = round(
            toks2 / max(1, (eng2.steps - s0_steps) * eng2.chunk), 2)
        if roof:
            res["decode_spec_vs_roofline"] = round(toks2 / sdt / roof, 4)
    except Exception as e:
        res["decode_spec_error"] = str(e)[:160]

    # speculative decoding on the PAGED engine (ISSUE 19): the same
    # repetition-heavy workload, but drafts + verify + acceptance ride
    # the single-dispatch megakernel program — launches_per_step is
    # the guard that spec verify stays at 2 launches (vs O(layers)).
    # This row died in r05 (RESOURCE_EXHAUSTED killed the engine build
    # and the old suite had no paged-spec row to notice); it is now
    # guarded by name in tools/bench_diff.py.
    try:
      if "spec_paged" in sections and eng2 is not None:
        from paddle_tpu.inference.paged_engine import PagedDecodeEngine
        n_spec = n_new2 if not smoke else 12
        need = s_pf + n_spec + spec_k
        engS = PagedDecodeEngine(
            None, n_pages=slots * (need // 128 + 2) + 2,
            max_slots=slots, steps_per_call=2 if smoke else 16,
            speculative_k=spec_k, share_weights_with=eng2)
        rs = np.random.RandomState(2)
        loops = [list(rs.randint(0, cfg.vocab_size, 8))
                 for _ in range(slots)]
        sp_prompts = [(lp * (s_pf // 8 + 1))[:s_pf] for lp in loops]
        for p in sp_prompts:  # warm (compiles + prefix registration)
            engS.submit(p, max_new_tokens=2)
        engS.run()
        reqs3 = [engS.submit(p, max_new_tokens=n_spec)
                 for p in sp_prompts]
        engS.step()
        pre3 = sum(len(r.tokens) for r in reqs3)
        s0s = engS.steps
        t0 = time.perf_counter()
        engS.run()
        sdt = time.perf_counter() - t0
        disp3 = engS.steps - s0s
        toks3 = sum(len(r.tokens) for r in reqs3) - pre3
        res["decode_spec_paged_tokens_per_sec"] = round(toks3 / sdt, 1)
        res["decode_spec_paged_tokens_per_step"] = round(
            toks3 / max(1, disp3 * engS.chunk), 2)
        if roof:
            res["decode_spec_paged_vs_roofline"] = round(
                toks3 / sdt / roof, 4)
        _prof_rows(engS, "decode_spec_paged", toks3 / sdt, disp3,
                   toks3, sdt)
        engS.kp = engS.vp = None
        del engS
    except Exception as e:
        res["decode_spec_paged_error"] = str(e)[:160]
    return res


def bench_serve(jax, jnp, peak, smoke=False):
    """SLO serving ladder (BENCH_SERVE, ISSUE 10): deterministic
    Poisson load through the continuous-batching FRONT-END
    (paddle_tpu/serving/) at a ladder of offered QPS fractions of the
    engine's measured capacity. Per rung: p50/p99 TTFT, p99 TPOT,
    goodput (tokens/s from in-deadline completions), completion
    fraction, and mean batch occupancy — at sub-saturation the
    occupancy floor is the "scheduler keeps the pipeline fed, not
    trickling singletons" check (asserted in test_bench_smoke and
    tools/ci.sh front). The workload is pinned by
    PT_SERVE_LOADGEN_SEED, so rungs are comparable across rounds."""
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu import stats as _stats
    from paddle_tpu.inference.decode_engine import DecodeEngine
    from paddle_tpu.models import gpt
    from paddle_tpu.serving import FrontEnd, loadgen

    if smoke:
        cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=128, d_model=32,
                            n_layers=2, n_heads=4, dtype=jnp.float32)
        slots, n_req, chunk = 4, 32, 2
        prompt_len, new_tokens = (4, 24), (8, 16)
    else:
        cfg = gpt.gpt3_125m(max_seq_len=1024)
        slots, n_req, chunk = 8, 64, 16
        prompt_len, new_tokens = (16, 192), (16, 96)
    model = gpt.GPT(cfg, seed=0)
    max_len = prompt_len[1] + new_tokens[1] + 8
    seed = loadgen.default_seed()

    def make_frontend():
        eng = DecodeEngine(model, max_slots=slots, max_len=max_len,
                           steps_per_call=chunk, warmup=True)
        return FrontEnd(eng)

    res = {"serve_slots": slots, "serve_requests_per_rung": n_req,
           "serve_loadgen_seed": seed}

    # capacity probe (closed loop, all slots busy): the QPS ladder is
    # expressed as fractions of THIS, so the rungs stay meaningful
    # across hardware and model sizes
    _stats.reset("serve/")
    fe = make_frontend()
    probe = loadgen.poisson_trace(
        n_req, qps=1e9, seed=seed, vocab=cfg.vocab_size,
        prompt_len=prompt_len, new_tokens=new_tokens)
    t0 = time.perf_counter()
    for a in probe:      # qps=1e9 -> all arrivals due immediately
        fe.submit(a.prompt, max_new_tokens=a.max_new_tokens)
    fe.run()
    dt = time.perf_counter() - t0
    toks = sum(len(r.tokens) for r in fe.results())
    cap_tps = toks / dt
    cap_rps = n_req / dt
    # pump-denominated capacity twin (requests per engine step): the
    # smoke rungs pace arrivals by PUMP COUNT (loadgen.replay_ticks),
    # so the arrival/serve interleaving is a pure function of the
    # trace — a loaded CI host can no longer bunch arrivals or starve
    # the server between them (the PR 15 flake, de-flaked here)
    cap_rpp = n_req / max(1, fe.engine.steps)
    res["serve_capacity_tokens_per_sec"] = round(cap_tps, 1)
    res["serve_capacity_rps"] = round(cap_rps, 2)

    # sub25/sub75 are BELOW capacity (the SLO-relevant regime: latency
    # should stay flat); over2x sustains a backlog, where a scheduler
    # that feeds the pipeline shows near-full batches and one that
    # trickles singletons shows ~1/slots occupancy
    for label, frac in (("sub25", 0.25), ("sub75", 0.75),
                        ("over2x", 2.0)):
        qps = max(0.1, frac * (cap_rpp if smoke else cap_rps))
        trace = loadgen.poisson_trace(
            n_req, qps=qps, seed=seed, vocab=cfg.vocab_size,
            prompt_len=prompt_len, new_tokens=new_tokens)
        _stats.reset("serve/")
        fe = make_frontend()
        t0 = time.perf_counter()

        def _submit(a):
            return fe.submit(a.prompt,
                             max_new_tokens=a.max_new_tokens,
                             deadline_s=a.deadline_s)
        if smoke:
            # tick-paced: trace seconds are PUMPS (qps above is
            # requests-per-pump) — deterministic under suite load
            reqs = loadgen.replay_ticks(trace, submit=_submit,
                                        pump=fe.step)
        else:
            reqs = loadgen.replay(trace, submit=_submit, pump=fe.step)
        fe.run()
        wall = time.perf_counter() - t0
        snap = _stats.snapshot("serve/")
        done = [r for r in reqs if r.status == "done"]
        good_toks = sum(len(r.tokens) for r in done)
        occ_n = snap.get("serve/batch_occupancy.count", 0)
        pfx = f"serve_{label}"
        res[f"{pfx}_offered_qps"] = round(qps, 2)
        res[f"{pfx}_p50_ttft_ms"] = round(
            snap.get("serve/ttft_s.p50", 0) * 1e3, 2)
        res[f"{pfx}_p99_ttft_ms"] = round(
            snap.get("serve/ttft_s.p99", 0) * 1e3, 2)
        res[f"{pfx}_p99_tpot_ms"] = round(
            snap.get("serve/tpot_s.p99", 0) * 1e3, 2)
        res[f"{pfx}_goodput_tokens_per_sec"] = round(good_toks / wall, 1)
        res[f"{pfx}_completed_frac"] = round(len(done) / n_req, 4)
        res[f"{pfx}_occupancy_mean"] = round(
            snap.get("serve/batch_occupancy.sum", 0) / occ_n, 4) \
            if occ_n else 0.0
        fed_n = snap.get("serve/fed_occupancy.count", 0)
        res[f"{pfx}_fed_occupancy_mean"] = round(
            snap.get("serve/fed_occupancy.sum", 0) / fed_n, 4) \
            if fed_n else None
        res[f"{pfx}_backfills"] = int(
            _stats.get("serve/queue_backfill", 0))
    return res


def bench_train_quant_comm(jax, jnp, peak, smoke=False):
    """Quantized-collective training row (MULTICHIP ladder, ISSUE 7):
    the SAME dp train step with the gradient sync at fp32 vs the int8/fp8
    block-scaled wire — step time plus the fixed-seed loss trajectory, so
    a wire-format regression shows as either a slowdown OR a trajectory
    split. Also reports the measured comm/bytes_wire compression ratio
    (≥3.5x is the int8 block-256 acceptance bar)."""
    n_dev = len(jax.devices())
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    if n_dev < 2 and not smoke:
        return {}  # one chip has no dp axis worth measuring
    import paddle_tpu.distributed as dist
    from paddle_tpu import stats as _stats
    from paddle_tpu.distributed import compression as _comp
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.models import gpt
    from paddle_tpu import optimizer as optim

    steps, warmup = (6, 1) if smoke else (20, 3)
    # fixed-seed trajectory compare wants fp32 math on both sides
    cfg = (gpt.gpt_tiny(max_seq_len=32, dtype=jnp.float32)
           if smoke or n_dev <= 8
           else gpt.gpt3_125m(max_seq_len=512, dtype=jnp.float32))
    model = gpt.GPT(cfg, seed=0)
    params, _ = model.split_params()
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2 * max(1, n_dev), cfg.max_seq_len)),
        jnp.int32)

    def loss_fn(p, tok):
        return gpt.lm_loss(model.merge_params(p)(tok), tok)

    res = {"train_quant_comm_devices": n_dev}
    prev_topo = mesh_lib.get_topology()
    try:
        # set_global=False: the model's GSPMD sharding constraints must
        # stay off — the compressed step is an explicit shard_map over
        # dp, where every axis is manual
        topo = dist.init_mesh(dp=max(1, n_dev), set_global=False)
        for method in (None, "int8", "fp8"):
            name = method or "fp32"
            try:
                _stats.reset("comm/")
                opt = optim.SGD(learning_rate=1e-2)
                p = {k: jnp.copy(v) for k, v in params.items()}
                st = opt.init(p)
                ef = (_comp.init_error_feedback(p, topo.mesh)
                      if method else ())
                step = _comp.build_compressed_dp_step(
                    loss_fn, opt, topo.mesh, method)
                for _ in range(warmup):
                    p, st, ef, loss = step(p, st, ef, tokens)
                _sync(loss)
                t0 = time.perf_counter()
                for _ in range(steps):
                    p, st, ef, loss = step(p, st, ef, tokens)
                _sync(loss)
                dt = (time.perf_counter() - t0) / steps
                res[f"train_quant_comm_{name}_step_ms"] = round(dt * 1e3,
                                                                2)
                res[f"train_quant_comm_{name}_loss"] = round(float(loss),
                                                             5)
                if method:
                    ratio = _stats.get("comm/compression_ratio", 0)
                    res[f"train_quant_comm_{name}_wire_ratio"] = round(
                        float(ratio), 3)
                    base = res.get("train_quant_comm_fp32_loss")
                    if base is not None:
                        res[f"train_quant_comm_{name}_loss_delta"] = \
                            round(float(loss) - base, 5)
            except Exception as e:  # one wire format must not erase the rest
                res[f"train_quant_comm_{name}_error"] = str(e)[:120]
    finally:
        mesh_lib.set_topology(prev_topo)
    return res


def bench_train_overlap(jax, jnp, peak, smoke=False):
    """Overlap-aware collectives row (MULTICHIP ladder, ISSUE 11): the
    SAME bucketed block-model train step with overlap scheduling on vs
    off, at fp32 and the quantized wire — step time plus the fixed-seed
    loss delta, so a scheduling regression shows as either a slowdown OR
    a trajectory split. Also records the span-tracer overlap accounting
    (comm/exposed_s, comm/overlap_frac) and reports overlap_frac
    alongside step ms, so a hardware recapture picks the measured
    exposed-comm number up for free."""
    n_dev = len(jax.devices())
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    if n_dev < 2 and not smoke:
        return {}
    from paddle_tpu import optimizer as optim
    from paddle_tpu import stats as _stats
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed import overlap as OV
    from paddle_tpu.observability import comm as obs_comm
    from paddle_tpu.observability import trace

    steps, warmup = (4, 1) if smoke else (20, 3)
    L, d, hidden, batch = ((3, 16, 32, 8) if smoke or n_dev <= 8
                           else (16, 1024, 4096, 256))
    params, stacked, emb, blk, lf = OV.mlp_block_model(L, d, hidden)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, d), jnp.float32)
    y = jnp.asarray(rs.randn(batch, 8), jnp.float32)

    res = {"train_overlap_devices": n_dev,
           "train_overlap_shape": f"L{L}xd{d}xh{hidden}"}
    prev_topo = mesh_lib.get_topology()
    try:
        topo = mesh_lib.init_mesh(fsdp=max(1, n_dev), set_global=False)
        for method in (None, "int8"):
            for on in (True, False):
                name = f"{method or 'fp32'}_{'on' if on else 'off'}"
                try:
                    opt = optim.SGD(learning_rate=1e-2)
                    sp, st, step = OV.overlap_parallel(
                        dict(params), emb, blk, lf, opt, topo.mesh,
                        stacked, comm_quant=method, overlap=on)
                    for _ in range(warmup):
                        sp, st, loss = step(sp, st, x, y)
                    _sync(loss)
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        sp, st, loss = step(sp, st, x, y)
                    _sync(loss)
                    dt = (time.perf_counter() - t0) / steps
                    res[f"train_overlap_{name}_step_ms"] = round(
                        dt * 1e3, 2)
                    res[f"train_overlap_{name}_loss"] = round(
                        float(loss), 5)
                except Exception as e:  # one config must not erase the rest
                    res[f"train_overlap_{name}_error"] = str(e)[:120]
            fmt = method or "fp32"
            on_l = res.get(f"train_overlap_{fmt}_on_loss")
            off_l = res.get(f"train_overlap_{fmt}_off_loss")
            if on_l is not None and off_l is not None:
                res[f"train_overlap_{fmt}_loss_delta"] = round(
                    on_l - off_l, 6)
        # span-tracer overlap accounting: trace a fresh step with the
        # ring enabled BEFORE the build, so the issue-time collective
        # spans land outside any compute span (nesting them all inside
        # one big span would pin exposed_s to 0 by construction), then
        # mark each executed step's dispatch window with a compute/step
        # span and account over the whole region. The result measures
        # how much of the host-side collective issue time fell outside
        # the step dispatch windows — the tracer's honest view (see
        # observability.comm: on-device truth needs an XLA profile; the
        # on/off step-time delta above is the on-device signal).
        # try/finally restores the tracer whatever happens; a ring the
        # user already had enabled is never cleared — the accountant
        # windows onto this row's own spans instead.
        was = trace.enabled()
        t0 = time.perf_counter()
        try:
            if not was:
                trace.clear()
                trace.enable()
            _stats.reset("comm/")
            sp, st, step = OV.overlap_parallel(
                dict(params), emb, blk, lf,
                optim.SGD(learning_rate=1e-2), topo.mesh, stacked,
                comm_quant="int8", overlap=True)
            # the compiling call runs UNWRAPPED: its issue-time
            # collective spans must not nest inside a compute span
            sp, st, loss = step(sp, st, x, y)
            _sync(loss)
            for _ in range(3):
                with trace.span("compute/step"):
                    sp, st, loss = step(sp, st, x, y)
                    _sync(loss)
            e, frac, busy = obs_comm.record_step_overlap(
                window=(t0, time.perf_counter()))
            res["train_overlap_exposed_s"] = round(e, 6)
            res["train_overlap_overlap_frac"] = round(frac, 4)
            res["train_overlap_comm_busy_s"] = round(busy, 6)
        except Exception as e:
            res["train_overlap_accounting_error"] = str(e)[:120]
        finally:
            if not was:
                trace.disable()
    finally:
        mesh_lib.set_topology(prev_topo)
    return res


def bench_train_numerics(jax, jnp, peak, smoke=False):
    """Training-numerics observability row (ISSUE 18): the SAME
    overlap block-model step with the in-graph stats pack disabled /
    every step / every 16 steps. The timed loop at EVERY>0 includes
    the host harvest (one packed-vector transfer + decode per sampled
    step) — the honest end-to-end cost of running instrumented. The
    EVERY=1 overhead fraction vs the uninstrumented build is the
    headline (acceptance: <5% on the tiny smoke shape)."""
    n_dev = len(jax.devices())
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    if n_dev < 2 and not smoke:
        return {}
    import os
    from paddle_tpu import optimizer as optim
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed import overlap as OV
    from paddle_tpu.observability import numerics as nm

    steps, warmup = (8, 2) if smoke else (20, 3)
    L, d, hidden, batch = ((3, 16, 32, 8) if smoke or n_dev <= 8
                           else (16, 1024, 4096, 256))
    params, stacked, emb, blk, lf = OV.mlp_block_model(L, d, hidden)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, d), jnp.float32)
    y = jnp.asarray(rs.randn(batch, 8), jnp.float32)

    res = {"train_numerics_devices": n_dev,
           "train_numerics_shape": f"L{L}xd{d}xh{hidden}"}
    prev_topo = mesh_lib.get_topology()
    prev_env = os.environ.get("PT_NUMERICS_EVERY")
    try:
        topo = mesh_lib.init_mesh(fsdp=max(1, n_dev), set_global=False)
        for every, name in ((0, "off"), (1, "every1"),
                            (16, "every16")):
            os.environ["PT_NUMERICS_EVERY"] = str(every)
            try:
                sp, st, step = OV.overlap_parallel(
                    dict(params), emb, blk, lf,
                    optim.SGD(learning_rate=1e-2), topo.mesh, stacked,
                    comm_quant="int8")
                mon = nm.Monitor.for_step(step) if every else None

                def run(n, sp, st, base=0):
                    loss = None
                    for i in range(n):
                        out = step(sp, st, x, y)
                        (sp, st, loss), packed = nm.split_out(out)
                        if mon is not None:
                            mon.ingest(packed, step=base + i)
                    return sp, st, loss

                sp, st, loss = run(warmup, sp, st)
                _sync(loss)
                t0 = time.perf_counter()
                sp, st, loss = run(steps, sp, st, base=warmup)
                _sync(loss)
                dt = (time.perf_counter() - t0) / steps
                res[f"train_numerics_{name}_step_ms"] = round(
                    dt * 1e3, 2)
                res[f"train_numerics_{name}_loss"] = round(
                    float(loss), 5)
            except Exception as e:  # one cadence must not erase the rest
                res[f"train_numerics_{name}_error"] = str(e)[:120]
        off = res.get("train_numerics_off_step_ms")
        on = res.get("train_numerics_every1_step_ms")
        if off and on is not None:
            res["train_numerics_overhead_frac"] = round(
                (on - off) / off, 4)
        # parity guard: the stats never feed back into the update
        l_off = res.get("train_numerics_off_loss")
        l_on = res.get("train_numerics_every1_loss")
        if l_off is not None and l_on is not None:
            res["train_numerics_loss_delta"] = round(l_on - l_off, 6)
    finally:
        if prev_env is None:
            os.environ.pop("PT_NUMERICS_EVERY", None)
        else:
            os.environ["PT_NUMERICS_EVERY"] = prev_env
        mesh_lib.set_topology(prev_topo)
    return res


def bench_serve_disagg(jax, jnp, peak, smoke=False):
    """Disaggregated-serving ladder row (ISSUE 12): the SAME
    over-saturation Poisson workload through (a) a symmetric
    two-replica paged baseline (round-robin placement) and (b) a
    disaggregated prefill+decode pair with the block-scaled KV wire —
    goodput + p99 TTFT for both, plus the KV-transfer row (logical vs
    wire bytes, compression ratio, transfer-latency percentiles) and
    the fleet prefix-hit counters on a repeated-system-prompt tail.
    Replicas are in-process FrontEnds (scheduling + wire effects, no
    IPC noise — the real-process path is tools/ci.sh disagg); runs
    LAST in the ladder per the PR 7/9/11 newest-row truncation rule."""
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu import stats as _stats
    from paddle_tpu.inference.paged_engine import PagedDecodeEngine
    from paddle_tpu.models import gpt
    from paddle_tpu.serving import FrontEnd, loadgen
    from paddle_tpu.serving import kv_transfer as kt

    if smoke:
        cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=512, d_model=32,
                            n_layers=2, n_heads=4, dtype=jnp.float32)
        slots, n_req, n_pages = 2, 16, 48
        prompt_len, new_tokens = (130, 280), (4, 10)
    else:
        cfg = gpt.gpt3_125m(max_seq_len=1024)
        slots, n_req, n_pages = 8, 48, 256
        prompt_len, new_tokens = (130, 500), (16, 64)
    model = gpt.GPT(cfg, seed=0)
    seed = loadgen.default_seed()
    res = {"serve_disagg_requests": n_req,
           "serve_disagg_kv_wire": kt.wire_format()}

    def trace_for(qps):
        return loadgen.poisson_trace(
            n_req, qps=qps, seed=seed, vocab=cfg.vocab_size,
            prompt_len=prompt_len, new_tokens=new_tokens)

    # capacity probe on ONE symmetric replica (closed loop), so the
    # over-saturation rung is a hardware-relative 2x
    _stats.reset("serve/")
    fe = FrontEnd(PagedDecodeEngine(model, n_pages=n_pages,
                                    max_slots=slots))
    t0 = time.perf_counter()
    for a in trace_for(1e9):
        fe.submit(a.prompt, max_new_tokens=a.max_new_tokens)
    fe.run()
    cap_rps = n_req / (time.perf_counter() - t0)
    res["serve_disagg_capacity_rps"] = round(cap_rps, 2)
    qps = max(0.1, 2.0 * cap_rps)     # over-saturation: 2x one replica

    def run_symmetric():
        fes = [FrontEnd(PagedDecodeEngine(model, n_pages=n_pages,
                                          max_slots=slots))
               for _ in range(2)]
        i = [0]

        def submit(a):
            i[0] += 1
            return fes[i[0] % 2].submit(
                a.prompt, max_new_tokens=a.max_new_tokens)

        def pump():
            for f in fes:
                f.step()

        t0 = time.perf_counter()
        reqs = loadgen.replay(trace_for(qps), submit=submit, pump=pump)
        for f in fes:
            f.run()
        return reqs, time.perf_counter() - t0

    def run_disagg():
        pe = PagedDecodeEngine(model, n_pages=n_pages, max_slots=slots,
                               prefill_only=True)
        de = FrontEnd(PagedDecodeEngine(model, n_pages=n_pages,
                                        max_slots=slots))
        open_pf = []

        def submit(a):
            # the prefill-only engine is role-tagged: its first-token
            # observation lands in serve/prefill_s, never serve/ttft_s
            # (the PR 12 t_first pre-mark workaround, retired) — the
            # row's p99 TTFT stays end-to-end decode-side samples only
            r = pe.submit(a.prompt, max_new_tokens=a.max_new_tokens)
            rec = [r, None, time.perf_counter()]
            open_pf.append(rec)
            return rec

        def pump():
            if any(not r.tokens and not r.done for r, _, _ in open_pf):
                pe.step()
                pe.drain()
            for rec in list(open_pf):
                r, _, t_sub = rec
                if r.failed or (r.done and rec[1] is None):
                    rec[1] = r          # finished on the prefill side
                    open_pf.remove(rec)
                elif r.tokens:
                    meta, k, v = pe.detach_handoff(r)
                    tx = time.perf_counter()
                    h, blob = kt.encode_kv_pages(k, v,
                                                 meta["n_tokens"])
                    k2, v2 = kt.decode_kv_pages(h, blob)
                    _stats.observe("serve/kv_transfer_s",
                                   time.perf_counter() - tx)
                    rec[1] = de.submit_handoff(meta, k2, v2,
                                               t_submit=t_sub)
                    open_pf.remove(rec)
            de.step()

        t0 = time.perf_counter()
        recs = loadgen.replay(trace_for(qps), submit=submit, pump=pump)
        while open_pf:
            pump()
        de.run()
        return [rec[1] if rec[1] is not None else rec[0]
                for rec in recs], time.perf_counter() - t0

    for label, runner in (("symmetric", run_symmetric),
                          ("disagg", run_disagg)):
        _stats.reset("serve/")
        reqs, wall = runner()
        snap = _stats.snapshot("serve/")
        # ServeRequests report status; raw engine Requests (prefill-
        # side finishes in the disagg run) report done/failed — an
        # unconditional status default would count FAILED engine
        # requests as done and inflate goodput
        done = [r for r in reqs
                if (r.status == "done" if hasattr(r, "status")
                    else (r.done and not r.failed))]
        toks = sum(len(r.tokens) for r in done)
        pfx = f"serve_disagg_{label}"
        res[f"{pfx}_offered_qps"] = round(qps, 2)
        res[f"{pfx}_goodput_tokens_per_sec"] = round(toks / wall, 1)
        res[f"{pfx}_p99_ttft_ms"] = round(
            snap.get("serve/ttft_s.p99", 0) * 1e3, 2)
        res[f"{pfx}_completed_frac"] = round(len(done) / n_req, 4)
        if label == "disagg":
            # the prefill phase's own latency histogram (role-tagged
            # metric — see serve/prefill_s in docs/observability.md)
            res["serve_disagg_prefill_p99_ms"] = round(
                snap.get("serve/prefill_s.p99", 0) * 1e3, 2)
            wire = _stats.get("serve/kv_transfer_bytes_wire")
            logical = _stats.get("serve/kv_transfer_bytes_logical")
            res["serve_disagg_kv_bytes_logical"] = int(logical)
            res["serve_disagg_kv_bytes_wire"] = int(wire)
            res["serve_disagg_kv_ratio"] = round(
                logical / wire, 2) if wire else None
            res["serve_disagg_kv_transfer_p50_ms"] = round(
                snap.get("serve/kv_transfer_s.p50", 0) * 1e3, 3)
            res["serve_disagg_kv_transfer_p99_ms"] = round(
                snap.get("serve/kv_transfer_s.p99", 0) * 1e3, 3)

    # fleet prefix-hit tail: two engines sharing a store; the second
    # replica's admission must hit the first's published pages
    from paddle_tpu import native
    if native.is_available():
        store = native.TCPStore("127.0.0.1", 0, is_master=True)
        try:
            from paddle_tpu.serving.disagg import FleetPrefixDirectory
            rs = __import__("numpy").random.RandomState(seed)
            sysp = [int(x) for x in rs.randint(0, cfg.vocab_size,
                                               size=260)]
            a = PagedDecodeEngine(model, n_pages=n_pages, max_slots=2)
            a.attach_fleet(FleetPrefixDirectory(store, "bench-a"))
            b = PagedDecodeEngine(model, n_pages=n_pages, max_slots=2)
            b.attach_fleet(FleetPrefixDirectory(store, "bench-b"))
            a.submit(sysp, max_new_tokens=4)
            a.run()
            _stats.reset("serve/fleet")
            b.submit(sysp, max_new_tokens=4)
            b.run()
            res["serve_disagg_fleet_hit_tokens"] = int(
                _stats.get("serve/fleet_prefix_hit_tokens"))
        finally:
            store.close()
    return res


def bench_fleet_churn(jax, jnp, peak, smoke=False):
    """Fleet-churn ladder row (ISSUE 14): the SAME Poisson workload
    through a two-replica fleet in steady state vs under a scripted
    KILL + SCALE event — one replica dies a third of the way in (its
    unfinished requests redistribute to the survivor from scratch,
    at-least-once), and a controller-style replacement joins at two
    thirds (paying its cold engine build, the spawn cost a real
    scale-up pays). Reports goodput, p99 TTFT, and completion for both
    phases plus the churn/steady goodput ratio. Replicas are
    in-process FrontEnds (scheduling + redistribution effects, no IPC
    noise — the real-process controller path is tools/ci.sh elastic);
    runs LAST in the ladder per the PR 7/9/11/12 newest-row truncation
    rule."""
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    from paddle_tpu import stats as _stats
    from paddle_tpu.inference.decode_engine import DecodeEngine
    from paddle_tpu.models import gpt
    from paddle_tpu.serving import FrontEnd, loadgen

    if smoke:
        cfg = gpt.GPTConfig(vocab_size=96, max_seq_len=160, d_model=32,
                            n_layers=2, n_heads=4, dtype=jnp.float32)
        slots, n_req, max_len = 2, 16, 96
        prompt_len, new_tokens = (6, 40), (4, 10)
    else:
        cfg = gpt.gpt3_125m(max_seq_len=512)
        slots, n_req, max_len = 8, 60, 320
        prompt_len, new_tokens = (16, 200), (8, 48)
    model = gpt.GPT(cfg, seed=0)
    seed = loadgen.default_seed()
    trace = None  # built after the capacity probe

    def mk():
        return FrontEnd(DecodeEngine(model, max_slots=slots,
                                     max_len=max_len))

    # capacity probe on ONE replica (closed loop): the offered rate is
    # hardware-relative, the churn window saturates the lone survivor
    _stats.reset("serve/")
    fe = mk()
    t0 = time.perf_counter()
    for a in loadgen.poisson_trace(n_req, qps=1e9, seed=seed,
                                   vocab=cfg.vocab_size,
                                   prompt_len=prompt_len,
                                   new_tokens=new_tokens):
        fe.submit(a.prompt, max_new_tokens=a.max_new_tokens)
    fe.run()
    cap_rps = n_req / (time.perf_counter() - t0)
    qps = max(0.1, 1.0 * cap_rps)   # two replicas run at ~50% load
    trace = loadgen.poisson_trace(n_req, qps=qps, seed=seed,
                                  vocab=cfg.vocab_size,
                                  prompt_len=prompt_len,
                                  new_tokens=new_tokens)
    kill_at = trace[n_req // 3].t
    replace_at = trace[(2 * n_req) // 3].t
    res = {"fleet_churn_requests": n_req,
           "fleet_churn_offered_qps": round(qps, 2),
           "fleet_churn_capacity_rps": round(cap_rps, 2)}

    def run(churn: bool):
        fes = [mk(), mk()]
        recs = []                     # [ServeRequest, replica idx, Arrival]
        state = {"killed": False, "replaced": False, "redist": 0,
                 "i": 0, "t0": time.perf_counter()}

        def submit(a):
            state["i"] += 1
            cand = [k for k, f in enumerate(fes) if f is not None]
            k = cand[state["i"] % len(cand)]
            r = fes[k].submit(a.prompt,
                              max_new_tokens=a.max_new_tokens)
            recs.append([r, k, a])
            return r

        def pump():
            t = time.perf_counter() - state["t0"]
            if (churn and not state["killed"] and t > kill_at
                    and any(k == 1 and not r.done
                            for r, k, _a in recs)):
                # the scripted kill — deferred past kill_at until the
                # victim actually HOLDS unfinished work (a fast box
                # could drain replica 1 between arrivals, and a kill
                # that loses nothing measures nothing; round-robin
                # keeps feeding it, so this fires within an arrival or
                # two). Its in-progress work is LOST; the router-side
                # at-least-once contract re-enters it on the survivor
                # from scratch.
                state["killed"] = True
                fes[1] = None
                for rec in recs:
                    r, k, _a = rec
                    if k == 1 and not r.done:
                        rec[0] = fes[0].submit(
                            _a.prompt,
                            max_new_tokens=_a.max_new_tokens)
                        rec[1] = 0
                        state["redist"] += 1
            if (churn and state["killed"] and not state["replaced"]
                    and t > replace_at):
                # the controller's replacement joins COLD (fresh
                # engine build = the real scale-up actuation cost)
                state["replaced"] = True
                fes[1] = mk()
            for f in fes:
                if f is not None:
                    f.step()

        loadgen.replay(trace, submit=submit, pump=pump)
        while any(not r.done for r, _k, _a in recs):
            pump()
        wall = time.perf_counter() - state["t0"]
        done = [r for r, _k, _a in recs if r.status == "done"]
        toks = sum(len(r.tokens) for r in done)
        return (toks / wall, len(done), state["redist"])

    for label, churn in (("steady", False), ("churn", True)):
        _stats.reset("serve/")
        goodput, n_done, redist = run(churn)
        snap = _stats.snapshot("serve/")
        pfx = f"fleet_churn_{label}"
        res[f"{pfx}_goodput_tokens_per_sec"] = round(goodput, 1)
        res[f"{pfx}_p99_ttft_ms"] = round(
            snap.get("serve/ttft_s.p99", 0) * 1e3, 2)
        res[f"{pfx}_completed_frac"] = round(n_done / n_req, 4)
        if churn:
            res["fleet_churn_redistributed"] = int(redist)
    steady = res.get("fleet_churn_steady_goodput_tokens_per_sec")
    churned = res.get("fleet_churn_churn_goodput_tokens_per_sec")
    if steady:
        res["fleet_churn_goodput_ratio"] = round(churned / steady, 3)

    # -- drain-with-migration phase (ISSUE 16): same trace, but at
    # kill_at replica 1 DRAINS — its in-flight requests migrate
    # mid-decode to replica 0 over the fp32 KV wire instead of being
    # lost (churn phase) or finished in place (PR 14 drains). The
    # latency row is the time to empty the draining replica; the dip
    # row is the goodput cost of the event vs steady state.
    def run_drain():
        from paddle_tpu.serving import kv_transfer
        fes = [mk(), mk()]
        recs = []
        state = {"i": 0, "t0": time.perf_counter(), "drained": False,
                 "migrated": 0, "drain_ms": 0.0}

        def submit(a):
            state["i"] += 1
            k = (state["i"] % 2) if not state["drained"] else 0
            r = fes[k].submit(a.prompt,
                              max_new_tokens=a.max_new_tokens)
            recs.append([r, k, a])
            return r

        def migrate_off():
            td = time.perf_counter()
            while True:
                open_recs = [rec for rec in recs
                             if rec[1] == 1 and not rec[0].done]
                if not open_recs:
                    break
                progress = False
                for rec in open_recs:
                    got = fes[1].detach_migrate(rec[0])
                    if got is None:
                        continue
                    if got["kv"]:
                        meta = got["meta"]
                        hdr, blob = kv_transfer.encode_kv_pages(
                            got["k"], got["v"],
                            n_tokens=meta["n_tokens"], wire="fp32")
                        k2, v2 = kv_transfer.decode_kv_pages(hdr, blob)
                        rec[0] = fes[0].submit_handoff(
                            dict(meta, wire=hdr["wire"]), k2, v2)
                    else:
                        rec[0] = fes[0].submit(
                            rec[2].prompt,
                            max_new_tokens=rec[2].max_new_tokens)
                    rec[1] = 0
                    state["migrated"] += 1
                    progress = True
                if not progress:
                    # mid-prefill stragglers: pump until they hold a
                    # token (per-request fallback would finish them in
                    # place; here they all become migratable)
                    fes[1].step()
            state["drain_ms"] = (time.perf_counter() - td) * 1e3
            state["drained"] = True

        def pump():
            t = time.perf_counter() - state["t0"]
            if not state["drained"] and t > kill_at:
                migrate_off()
            for k, f in enumerate(fes):
                if k == 1 and state["drained"]:
                    continue
                f.step()

        loadgen.replay(trace, submit=submit, pump=pump)
        while any(not r.done for r, _k, _a in recs):
            pump()
        wall = time.perf_counter() - state["t0"]
        done = [r for r, _k, _a in recs if r.status == "done"]
        toks = sum(len(r.tokens) for r in done)
        return (toks / wall, len(done), state["migrated"],
                state["drain_ms"])

    _stats.reset("serve/")
    d_goodput, d_done, migrated, drain_ms = run_drain()
    res["fleet_churn_drain_goodput_tokens_per_sec"] = round(d_goodput, 1)
    res["fleet_churn_drain_completed_frac"] = round(d_done / n_req, 4)
    res["fleet_churn_drain_migrated"] = int(migrated)
    res["fleet_churn_drain_latency_ms"] = round(drain_ms, 2)
    if steady:
        res["fleet_churn_drain_goodput_dip_frac"] = round(
            max(0.0, 1.0 - d_goodput / steady), 4)

    # -- router-failover phase (ISSUE 17): the same trace, but at
    # kill_at the ROUTER's accounting dies (replicas survive) and a
    # successor rebuilds it from the real FrontEnd-side RequestJournal
    # (serving/scheduler.py). Recovery = journal replay + re-accepting
    # results the replicas retained (first-result-wins, no re-serve;
    # in-flight work keeps decoding and dedups replica-side). The
    # recovery_s row tracks the client-visible placement gap; the
    # republished row counts retained results the successor accepted
    # without re-serving; the dip row is the goodput cost vs steady.
    def run_failover():
        import os as _os
        import tempfile as _tf
        from paddle_tpu.serving.scheduler import RequestJournal
        path = _os.path.join(_tf.mkdtemp(prefix="pt-bench-ha-"),
                             "requests.jsonl")
        state = {"i": 0, "t0": time.perf_counter(), "failed": False,
                 "recovery_s": 0.0, "republished": 0,
                 "journal": RequestJournal(path)}
        fes = [mk(), mk()]
        recs = {}                 # req_id -> [req, arrival, journaled]
        lagged = set()            # done ids awaiting the journal beat

        def submit(a):
            state["i"] += 1
            req_id = f"rq-{state['i']:06d}"
            state["journal"].append_submit(
                {"id": req_id, "prompt": list(a.prompt),
                 "max_new_tokens": a.max_new_tokens})
            r = fes[state["i"] % 2].submit(
                a.prompt, max_new_tokens=a.max_new_tokens)
            recs[req_id] = [r, a, False]
            return r

        def pump():
            t = time.perf_counter() - state["t0"]
            if not state["failed"] and t > kill_at:
                state["failed"] = True
                t_rec = time.perf_counter()
                state["journal"].close()
                payloads, results = RequestJournal.replay(path)
                state["journal"] = RequestJournal(path)   # successor
                for q in payloads:
                    if q in results:
                        continue
                    rec = recs[q]
                    if rec[0].done:
                        # the replica retained this terminal result;
                        # the successor accepts it instead of
                        # re-serving (first-result-wins)
                        state["journal"].append_result(
                            q, {"status": rec[0].status})
                        rec[2] = True
                        state["republished"] += 1
                    # else: re-placed at-least-once; the replica
                    # still decoding it dedups the replay, so the
                    # request simply continues
                lagged.clear()
                state["recovery_s"] = time.perf_counter() - t_rec
            # journal terminal results one pump-beat late — the lag a
            # real router's poll cadence pays, and the window the
            # republished row measures
            for q in lagged:
                rec = recs[q]
                if not rec[2]:
                    state["journal"].append_result(
                        q, {"status": rec[0].status})
                    rec[2] = True
            lagged.clear()
            for q, rec in recs.items():
                if rec[0].done and not rec[2]:
                    lagged.add(q)
            for f in fes:
                f.step()

        loadgen.replay(trace, submit=submit, pump=pump)
        while any(not rec[0].done for rec in recs.values()):
            pump()
        wall = time.perf_counter() - state["t0"]
        state["journal"].close()
        done = [rec[0] for rec in recs.values()
                if rec[0].status == "done"]
        toks = sum(len(r.tokens) for r in done)
        return (toks / wall, len(done), state["recovery_s"],
                state["republished"])

    _stats.reset("serve/")
    f_goodput, f_done, recovery_s, republished = run_failover()
    res["fleet_churn_failover_goodput_tokens_per_sec"] = round(
        f_goodput, 1)
    res["fleet_churn_failover_completed_frac"] = round(
        f_done / n_req, 4)
    res["fleet_churn_failover_recovery_s"] = round(recovery_s, 4)
    res["fleet_churn_failover_republished"] = int(republished)
    if steady:
        res["fleet_churn_failover_goodput_dip_frac"] = round(
            max(0.0, 1.0 - f_goodput / steady), 4)

    # -- reshape wall-clock (ISSUE 16 tentpole axis): the SAME
    # (mesh, layout) hop — fsdp4(stacked) → tp2(per-layer) — via the
    # in-HBM redistribute pass vs the checkpoint round trip it
    # replaces (save + load_resharded to/from disk)
    if len(jax.devices()) >= 4:
        import tempfile
        from paddle_tpu import optimizer as optim
        from paddle_tpu.distributed import checkpoint as ckpt
        from paddle_tpu.distributed import mesh as mesh_lib
        from paddle_tpu.distributed import redistribute as redist
        opt = optim.AdamW(learning_rate=1e-3)
        mesh_lib.set_topology(None)
        topo_a = mesh_lib.init_mesh(fsdp=4, devices=jax.devices()[:4])
        pa, sa = gpt.init_train_state(model, opt, topo_a.mesh,
                                      stacked=True)
        src = {"params": pa, "opt_state": sa}
        mesh_lib.set_topology(None)
        topo_b = mesh_lib.init_mesh(tp=2, devices=jax.devices()[:2])
        pb, sb = gpt.init_train_state(model, opt, topo_b.mesh)
        dst = {"params": pb, "opt_state": sb}
        t0 = time.perf_counter()
        moved = redist.redistribute(src, dst, mesh=topo_b.mesh)
        jax.block_until_ready(moved)
        res["fleet_churn_reshard_inplace_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        root = tempfile.mkdtemp()
        t0 = time.perf_counter()
        ckpt.save_state(src, f"{root}/r")
        restored = ckpt.load_resharded(f"{root}/r", dst)
        jax.block_until_ready(restored)
        res["fleet_churn_reshard_ckpt_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        mesh_lib.set_topology(None)
    return res


def bench_train_sharded_stacked(jax, jnp, peak, smoke=False):
    """Sharded scan-over-layers row (MULTICHIP ladder, ISSUE 8): the SAME
    fsdp×tp GSPMD train step with per-layer vs pre-stacked block weights.
    Until this round the two were mutually exclusive — stacked refused
    any mesh with size > 1, so sharded runs paid the in-trace stack copy
    (~2x block-param HBM) every step. Reports step time, per-chip peak
    memory (XLA's analysis of the exact executable), and the fixed-seed
    loss delta: a stacked-layout regression shows as a slowdown, a
    memory blowup, OR a trajectory split."""
    n_dev = len(jax.devices())
    if jax.default_backend() in ("cpu",) and not smoke:
        return {}
    if n_dev < 2 and not smoke:
        return {}
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.models import gpt
    from paddle_tpu import optimizer as optim

    steps, warmup = (3, 1) if smoke else (10, 3)
    tp = 2 if n_dev % 2 == 0 else 1
    fsdp = max(1, n_dev // tp)
    cfg = (gpt.gpt_tiny(max_seq_len=32, dtype=jnp.float32)
           if smoke or n_dev <= 8
           else gpt.gpt3_350m(max_seq_len=1024, remat=True))
    batch = 2 * fsdp  # batch splits over (dp, fsdp)
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, cfg.max_seq_len)), jnp.int32)
    rng = jax.random.PRNGKey(0)

    res = {"train_sharded_stacked_devices": n_dev,
           "train_sharded_stacked_mesh": f"fsdp{fsdp}xtp{tp}"}
    prev_topo = mesh_lib.get_topology()
    try:
        topo = mesh_lib.init_mesh(fsdp=fsdp, tp=tp)
        for name, stacked in (("per_layer", False), ("stacked", True)):
            try:
                model = gpt.GPT(cfg, seed=0)
                opt = optim.AdamW(learning_rate=1e-4, weight_decay=0.01)
                params, opt_state = gpt.init_train_state(
                    model, opt, topo.mesh, stacked=stacked)
                step = gpt.build_train_step(model, opt, topo.mesh)
                try:
                    # per-chip peak from XLA's analysis of the lowered
                    # program (analysis only: the timed loop runs the
                    # jitted step, which re-specializes if the sharding
                    # fixed point differs from the init placement)
                    ma = step.lower(params, opt_state, tokens,
                                    rng).compile().memory_analysis()
                    res[f"train_sharded_stacked_{name}_peak_mb"] = round(
                        (ma.temp_size_in_bytes + ma.output_size_in_bytes)
                        / 2**20)
                except Exception:
                    pass
                for _ in range(warmup):
                    params, opt_state, loss = step(params, opt_state,
                                                   tokens, rng)
                _sync(loss)
                t0 = time.perf_counter()
                for _ in range(steps):
                    params, opt_state, loss = step(params, opt_state,
                                                   tokens, rng)
                _sync(loss)
                dt = (time.perf_counter() - t0) / steps
                res[f"train_sharded_stacked_{name}_step_ms"] = round(
                    dt * 1e3, 2)
                res[f"train_sharded_stacked_{name}_loss"] = round(
                    float(loss), 5)
            except Exception as e:  # one layout must not erase the other
                res[f"train_sharded_stacked_{name}_error"] = str(e)[:120]
        base = res.get("train_sharded_stacked_per_layer_loss")
        st = res.get("train_sharded_stacked_stacked_loss")
        if base is not None and st is not None:
            res["train_sharded_stacked_loss_delta"] = round(st - base, 5)
    finally:
        mesh_lib.set_topology(prev_topo)
    return res


def _hbm_gbps(device) -> float:
    """Per-chip HBM bandwidth (GB/s) from the cost model's single spec
    table — no second copy to drift."""
    from paddle_tpu.cost_model import _peak
    return _peak(device)[1] / 1e9


if __name__ == "__main__":
    sys.exit(main())
